"""Models: shapes, dtypes, padding-independence, losses."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psana_ray_tpu.models import PeakNetUNet, ResNet18, ResNet50, panels_to_nhwc
from psana_ray_tpu.models.heads import nhwc_to_panels
from psana_ray_tpu.models.losses import masked_sigmoid_focal, masked_softmax_xent


class TestHeads:
    def test_panels_to_channels(self):
        x = jnp.arange(2 * 3 * 4 * 5.0).reshape(2, 3, 4, 5)
        y = panels_to_nhwc(x, "channels")
        assert y.shape == (2, 4, 5, 3)
        np.testing.assert_array_equal(np.asarray(y[0, :, :, 1]), np.asarray(x[0, 1]))

    def test_panels_to_batch_roundtrip(self):
        x = jnp.arange(2 * 3 * 4 * 5.0).reshape(2, 3, 4, 5)
        y = panels_to_nhwc(x, "batch")
        assert y.shape == (6, 4, 5, 1)
        np.testing.assert_array_equal(np.asarray(nhwc_to_panels(y, 3)), np.asarray(x))


class TestResNet:
    def test_resnet18_forward(self):
        model = ResNet18(num_classes=2, width=16)
        x = jnp.ones((2, 64, 64, 4))
        vars_ = model.init(jax.random.key(0), x)
        out = model.apply(vars_, x)
        assert out.shape == (2, 2)
        assert out.dtype == jnp.float32  # logits in f32

    def test_resnet50_param_count(self):
        # full-width ResNet-50: ~25.6M params in the torchvision layout;
        # ours differs (GroupNorm, SiLU, panel channels) but must be same
        # order: check the 4-stage bottleneck structure produced ~23-30M
        model = ResNet50(num_classes=2, width=64)
        vars_ = jax.eval_shape(
            model.init, jax.random.key(0), jnp.ones((1, 224, 224, 3), jnp.float32)
        )
        n = sum(np.prod(v.shape) for v in jax.tree.leaves(vars_))
        assert 20e6 < n < 32e6, f"param count {n/1e6:.1f}M out of ResNet-50 range"

    def test_rows_independent(self):
        # GroupNorm: padded rows must not change real rows' logits
        model = ResNet18(num_classes=2, width=16)
        real = jnp.asarray(np.random.default_rng(0).normal(size=(1, 64, 64, 4)), jnp.float32)
        vars_ = model.init(jax.random.key(0), jnp.zeros((2, 64, 64, 4)))
        alone = model.apply(vars_, real)
        padded = model.apply(vars_, jnp.concatenate([real, jnp.zeros_like(real)]))
        np.testing.assert_allclose(np.asarray(alone[0]), np.asarray(padded[0]), atol=2e-2)


class TestUNet:
    def test_forward_shape(self):
        model = PeakNetUNet(features=(8, 16, 32), num_classes=1)
        x = jnp.ones((2, 64, 96, 1))
        vars_ = model.init(jax.random.key(0), x)
        out = model.apply(vars_, x)
        assert out.shape == (2, 64, 96, 1)
        assert out.dtype == jnp.float32

    def test_epix_panel_geometry(self):
        # epix10k2M panel 352x384 through depth-4 U-Net (divisible by 8)
        model = PeakNetUNet(features=(4, 8, 16, 32))
        x = jnp.ones((1, 352, 384, 1))
        out = model.apply(model.init(jax.random.key(0), x), x)
        assert out.shape == (1, 352, 384, 1)

    def test_panel_as_batch_path(self):
        frames = jnp.ones((2, 4, 32, 64))  # [B,P,H,W]
        x = panels_to_nhwc(frames, "batch")
        model = PeakNetUNet(features=(4, 8))
        out = model.apply(model.init(jax.random.key(0), x), x)
        masks = nhwc_to_panels(out, 4)
        assert masks.shape == (2, 4, 32, 64)


class TestLosses:
    def test_xent_ignores_padding(self):
        logits = jnp.asarray([[10.0, -10.0], [0.0, 0.0], [-5.0, 5.0]])
        labels = jnp.asarray([0, 1, 0])
        full = masked_softmax_xent(logits, labels, jnp.asarray([1, 1, 0]))
        sub = masked_softmax_xent(logits[:2], labels[:2], jnp.asarray([1, 1]))
        assert float(full) == pytest.approx(float(sub))

    def test_xent_all_padded_finite(self):
        out = masked_softmax_xent(jnp.ones((2, 3)), jnp.zeros((2,), jnp.int32), jnp.zeros((2,)))
        assert np.isfinite(float(out))

    def test_focal_downweights_easy(self):
        t = jnp.zeros((1, 8, 8, 1))
        easy = jnp.full((1, 8, 8, 1), -9.0)  # confident background
        hard = jnp.full((1, 8, 8, 1), 0.0)
        assert float(masked_sigmoid_focal(easy, t)) < float(masked_sigmoid_focal(hard, t))

    def test_focal_padding(self):
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.normal(size=(3, 4, 4, 1)), jnp.float32)
        targets = jnp.asarray(rng.random((3, 4, 4, 1)) < 0.1, jnp.float32)
        full = masked_sigmoid_focal(logits, targets, jnp.asarray([1, 1, 0]))
        sub = masked_sigmoid_focal(logits[:2], targets[:2], jnp.asarray([1, 1]))
        assert float(full) == pytest.approx(float(sub), rel=1e-5)


class TestMergeBlockEquivalence:
    def test_split_weights_equal_concat_conv(self, rng):
        """conv_a(up) + conv_b(skip) must equal conv(concat([up, skip]))
        with the kernel stitched along its input-channel axis — the
        identity MergeBlock relies on to skip the concat copy."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from flax.core import meta

        from psana_ray_tpu.models.unet import MergeBlock

        f = 8
        up = jnp.asarray(rng.normal(size=(2, 8, 8, f)).astype(np.float32))
        skip = jnp.asarray(rng.normal(size=(2, 8, 8, f)).astype(np.float32))
        block = MergeBlock(features=f, dtype=jnp.float32, norm="frozen")
        variables = block.init(jax.random.key(0), up, skip)
        got = block.apply(variables, up, skip)

        p = meta.unbox(variables)["params"]
        k = jnp.concatenate(
            [p["merge_up"]["kernel"], p["merge_skip"]["kernel"]], axis=2
        )  # [3,3,2f,f]
        y = jax.lax.conv_general_dilated(
            jnp.concatenate([up, skip], axis=-1), k, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        aff0 = p["FrozenAffine_0"]
        y = y * aff0["scale"] + aff0["bias"]
        y = jax.nn.silu(y)
        y = jax.lax.conv_general_dilated(
            y, p["Conv_0"]["kernel"], (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        aff1 = p["FrozenAffine_1"]
        ref = jax.nn.silu(y * aff1["scale"] + aff1["bias"])
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_unet_frozen_norm_runs(self, rng):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from psana_ray_tpu.models import PeakNetUNet

        model = PeakNetUNet(features=(8, 16), norm="frozen")
        x = jnp.asarray(rng.normal(size=(2, 16, 16, 1)).astype(np.float32))
        v = model.init(jax.random.key(0), x)
        out = model.apply(v, x)
        assert out.shape == (2, 16, 16, 1)
        assert np.isfinite(np.asarray(out)).all()

    def test_upsample2x_matches_resize_nearest(self, rng):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from psana_ray_tpu.models.unet import _upsample2x

        x = jnp.asarray(rng.normal(size=(2, 5, 6, 3)).astype(np.float32))
        ref = jax.image.resize(x, (2, 10, 12, 3), "nearest")
        np.testing.assert_array_equal(np.asarray(_upsample2x(x)), np.asarray(ref))


class TestUNetTPU:
    """PeakNet-TPU (models/unet_tpu.py): the MXU-shaped redesign — s2d
    stem, wide features at half resolution, depth-to-space logit head."""

    def test_s2d_d2s_roundtrip(self):
        from psana_ray_tpu.models.unet_tpu import depth_to_space, space_to_depth

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(2, 8, 12, 3)).astype(np.float32))
        np.testing.assert_array_equal(
            np.asarray(depth_to_space(space_to_depth(x, 2), 2)), np.asarray(x)
        )

    def test_s2d_is_pixel_unshuffle(self):
        from psana_ray_tpu.models.unet_tpu import space_to_depth

        x = jnp.arange(16, dtype=jnp.float32).reshape(1, 4, 4, 1)
        p = space_to_depth(x, 2)
        assert p.shape == (1, 2, 2, 4)
        # packed channels are the 2x2 neighborhood of each output pixel
        np.testing.assert_array_equal(np.asarray(p[0, 0, 0]), [0, 1, 4, 5])
        np.testing.assert_array_equal(np.asarray(p[0, 1, 1]), [10, 11, 14, 15])

    def test_forward_shape_per_pixel_logits(self):
        from psana_ray_tpu.models import PeakNetUNetTPU

        model = PeakNetUNetTPU(features=(8, 16, 32), num_classes=1)
        x = jnp.ones((2, 32, 48, 1))
        out = model.apply(model.init(jax.random.key(0), x), x)
        assert out.shape == (2, 32, 48, 1)  # one logit per ORIGINAL pixel
        assert out.dtype == jnp.float32

    def test_epix_panel_geometry(self):
        from psana_ray_tpu.models import PeakNetUNetTPU

        model = PeakNetUNetTPU(features=(4, 8, 16, 32))
        x = jnp.ones((1, 352, 384, 1))  # 16 | 352, 16 | 384
        out = model.apply(model.init(jax.random.key(0), x), x)
        assert out.shape == (1, 352, 384, 1)

    def test_rejects_misaligned_extents(self):
        from psana_ray_tpu.models import PeakNetUNetTPU

        model = PeakNetUNetTPU(features=(8, 16))
        x = jnp.ones((1, 30, 32, 1))  # 30 % 4 != 0
        with pytest.raises(ValueError, match="divisible"):
            model.init(jax.random.key(0), x)

    def test_trainable_group_norm_grads(self):
        from psana_ray_tpu.models import PeakNetUNetTPU

        model = PeakNetUNetTPU(features=(8, 16), norm="group")
        x = jnp.ones((1, 16, 16, 1))
        variables = model.init(jax.random.key(0), x)

        def loss(v):
            return jnp.sum(model.apply(v, x) ** 2)

        g = jax.grad(loss)(variables)
        leaves = jax.tree.leaves(g)
        assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
        assert any(float(jnp.max(jnp.abs(l))) > 0 for l in leaves)

    def test_classic_unet_rejects_misaligned_extents(self):
        model = PeakNetUNet(features=(8, 16, 32))
        x = jnp.ones((1, 34, 32, 1))  # 34 % 4 != 0: fail loudly at the door
        with pytest.raises(ValueError, match="divisible"):
            model.init(jax.random.key(0), x)


class TestHostInit:
    """host_init / eval_shape_init: the backend-independent param build.

    The fallback exists for environments whose JAX plugin registers ONLY a
    remote TPU platform (no cpu backend to jit init on; remote init is
    minutes — PERF_NOTES.md). On this CPU test host we call the fallback
    directly."""

    def test_eval_shape_init_matches_real_init_structure(self):
        from psana_ray_tpu.models.init import eval_shape_init

        model = ResNet18(num_classes=2, width=16, norm="frozen")
        shape = (1, 32, 32, 4)
        fake = eval_shape_init(model, shape)
        real = model.init(jax.random.key(0), jnp.zeros(shape))
        assert jax.tree_util.tree_structure(fake) == jax.tree_util.tree_structure(real)
        for (pf, lf), (pr, lr) in zip(
            jax.tree_util.tree_leaves_with_path(fake),
            jax.tree_util.tree_leaves_with_path(real),
        ):
            assert pf == pr
            assert lf.shape == lr.shape, pf
            assert np.dtype(lf.dtype) == np.dtype(lr.dtype), pf

    def test_eval_shape_init_forward_is_sane(self):
        # conventions (kernel ~ 1/sqrt(fan_in), scale=1, bias=0) must keep
        # activations O(1) through the full stack: finite, nonzero logits
        from psana_ray_tpu.models.init import eval_shape_init

        model = ResNet18(num_classes=2, width=16, norm="frozen")
        fake = eval_shape_init(model, (1, 32, 32, 4))
        out = model.apply(fake, jnp.ones((2, 32, 32, 4)))
        arr = np.asarray(out, np.float32)
        assert np.isfinite(arr).all()
        assert np.abs(arr).max() > 0
        assert np.abs(arr).max() < 1e3

    def test_eval_shape_init_naming_conventions_fire(self):
        # the leaf-name heuristic must see through flax's partitioning
        # boxes (paths end in GetAttrKey('value')): norm scales exactly 1,
        # biases exactly 0, conv kernels fan-in-scaled — NOT the generic
        # 0.02*randn else-branch for everything
        from flax.core import meta

        from psana_ray_tpu.models.init import eval_shape_init

        model = ResNet18(num_classes=2, width=16, norm="frozen")
        fake = meta.unbox(eval_shape_init(model, (1, 32, 32, 4)))["params"]
        stem_norm = fake["stem_norm"]
        np.testing.assert_array_equal(np.asarray(stem_norm["scale"]), 1.0)
        np.testing.assert_array_equal(np.asarray(stem_norm["bias"]), 0.0)
        k = np.asarray(fake["stem"]["kernel"], np.float32)
        fan_in = float(np.prod(k.shape[:-1]))
        assert 0.5 / np.sqrt(fan_in) < k.std() < 2.0 / np.sqrt(fan_in)

    def test_eval_shape_init_unet_frozen(self):
        from psana_ray_tpu.models import PeakNetUNetTPU
        from psana_ray_tpu.models.init import eval_shape_init

        model = PeakNetUNetTPU(features=(8, 16), norm="frozen")
        fake = eval_shape_init(model, (1, 16, 16, 1))
        out = model.apply(fake, jnp.ones((1, 16, 16, 1)))
        assert out.shape == (1, 16, 16, 1)
        assert np.isfinite(np.asarray(out, np.float32)).all()

    def test_host_init_prefers_cpu_backend_when_available(self):
        # on this host a cpu backend exists, so host_init must be
        # bit-identical to the model's own jitted init
        from psana_ray_tpu.models import host_init

        model = ResNet18(num_classes=2, width=16)
        shape = (1, 32, 32, 4)
        got = host_init(model, shape)
        want = jax.jit(model.init)(jax.random.key(0), jnp.zeros(shape))
        for (pg, lg), (pw, lw) in zip(
            jax.tree_util.tree_leaves_with_path(got),
            jax.tree_util.tree_leaves_with_path(want),
        ):
            assert pg == pw
            np.testing.assert_array_equal(np.asarray(lg), np.asarray(lw))
