"""Observability layer (psana_ray_tpu.obs): registry, Prometheus export,
queue-health RPC, stall detection.

Strategy mirrors SURVEY.md §4 — in-process units, no sleeps where the API
lets us drive time explicitly (StallDetector.poll_once takes ``now``)."""

import json
import re
import threading
import time
import urllib.request

import numpy as np
import pytest

from psana_ray_tpu.obs import (
    EVENT_BACKPRESSURE,
    EVENT_CONSUMER_STALL,
    EVENT_PRODUCER_IDLE,
    MetricsRegistry,
    MetricsServer,
    StallDetector,
    start_metrics_server,
)
from psana_ray_tpu.transport.ring import RingBuffer
from psana_ray_tpu.utils.metrics import LatencyStats, PipelineMetrics

# Prometheus exposition text-format 0.0.4 sample line:
#   name{label="value"} 1.23
_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?'
    r' (?P<value>-?(?:\d+\.?\d*(?:e[+-]?\d+)?|nan|inf|-inf))$',
    re.IGNORECASE,
)


def parse_prometheus(text):
    """Validate + parse exposition text: returns {(name, labels): value}.
    Raises on any line that is neither a comment nor a valid sample, and
    on samples appearing before their HELP/TYPE headers."""
    samples = {}
    typed = set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            typed.add(line.split()[2])
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed exposition line: {line!r}"
        assert m.group("name") in typed, f"sample before HELP/TYPE: {line!r}"
        samples[(m.group("name"), m.group("labels") or "")] = float(m.group("value"))
    return samples


class TestRegistry:
    def test_snapshot_shapes(self):
        reg = MetricsRegistry()
        pm = PipelineMetrics()
        pm.observe_frame(100)
        pm.observe_batch(4, 0.002, nbytes=400)
        reg.register("consumer", pm)
        reg.register("queue", lambda: {"depth": 3, "puts": 10})
        snap = reg.snapshot()
        assert snap["consumer"]["frames_total"] == 5
        assert snap["consumer"]["bytes_total"] == 500
        assert snap["consumer"]["batches_total"] == 1
        assert snap["queue"] == {"depth": 3, "puts": 10}
        json.dumps(snap)  # JSON-safe contract

    def test_snapshot_survives_dead_source(self):
        reg = MetricsRegistry()

        def boom():
            raise RuntimeError("dead transport")

        reg.register("dead", boom)
        reg.register("alive", lambda: {"depth": 1})
        snap = reg.snapshot()
        assert snap["alive"] == {"depth": 1}
        assert "error" in snap["dead"]

    def test_render_prometheus_valid_and_typed(self):
        reg = MetricsRegistry()
        pm = PipelineMetrics()
        for _ in range(8):
            pm.observe_frame(1000)
        pm.observe_batch(8, 0.004, nbytes=0)
        reg.register("consumer", pm)
        text = reg.render_prometheus()
        samples = parse_prometheus(text)
        assert samples[("psana_ray_frames_total", 'source="consumer"')] == 16.0
        assert samples[("psana_ray_bytes_total", 'source="consumer"')] == 8000.0
        assert samples[("psana_ray_batches_total", 'source="consumer"')] == 1.0
        # quantile gauges from the step-latency reservoir
        assert ("psana_ray_step_latency_p50_ms", 'source="consumer"') in samples
        assert ("psana_ray_step_latency_p99_ms", 'source="consumer"') in samples
        # counter/gauge typing convention
        assert "# TYPE psana_ray_frames_total counter" in text
        assert "# TYPE psana_ray_step_latency_p50_ms gauge" in text

    def test_render_escapes_and_sanitizes(self):
        reg = MetricsRegistry()
        reg.register('we"ird\nsource', {"bad-metric name": 1})
        text = reg.render_prometheus()
        samples = parse_prometheus(text)
        assert samples == {("psana_ray_bad_metric_name", 'source="we\\"ird\\nsource"'): 1.0}

    def test_last_registration_wins(self):
        reg = MetricsRegistry()
        reg.register("q", {"depth": 1})
        reg.register("q", {"depth": 2})
        assert reg.snapshot()["q"] == {"depth": 2}
        reg.unregister("q")
        assert reg.snapshot() == {}

    def test_non_finite_and_non_numeric_leaves_skipped(self):
        reg = MetricsRegistry()
        reg.register("q", {"depth": 2, "rate": float("inf"), "name": "epix", "flag": True})
        samples = parse_prometheus(reg.render_prometheus())
        assert samples == {
            ("psana_ray_depth", 'source="q"'): 2.0,
            ("psana_ray_flag", 'source="q"'): 1.0,
        }


class TestExporter:
    def test_http_round_trip(self):
        """Acceptance: scrape the endpoint, get valid Prometheus text with
        frames/bytes/batches counters and p50/p99 gauges; /healthz serves
        the same registry as JSON."""
        reg = MetricsRegistry()
        pm = PipelineMetrics(queue=RingBuffer(8))
        for _ in range(3):
            pm.observe_frame(64)
        pm.observe_batch(3, 0.001)
        reg.register("consumer", pm)
        with MetricsServer(registry=reg, host="127.0.0.1", port=0) as srv:
            base = f"http://127.0.0.1:{srv.port}"
            with urllib.request.urlopen(f"{base}/metrics", timeout=5) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith("text/plain")
                text = resp.read().decode()
            samples = parse_prometheus(text)
            assert samples[("psana_ray_frames_total", 'source="consumer"')] == 6.0
            assert samples[("psana_ray_bytes_total", 'source="consumer"')] == 192.0
            assert samples[("psana_ray_batches_total", 'source="consumer"')] == 1.0
            assert ("psana_ray_step_latency_p50_ms", 'source="consumer"') in samples
            assert ("psana_ray_step_latency_p99_ms", 'source="consumer"') in samples
            # queue stats ride the same snapshot (attach_queue contract)
            assert ("psana_ray_queue_depth", 'source="consumer"') in samples
            with urllib.request.urlopen(f"{base}/healthz", timeout=5) as resp:
                health = json.loads(resp.read().decode())
            assert health["consumer"]["frames_total"] == 6
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"{base}/nope", timeout=5)

    def test_scrape_reflects_live_updates(self):
        reg = MetricsRegistry()
        pm = PipelineMetrics()
        reg.register("p", pm)
        with MetricsServer(registry=reg, host="127.0.0.1", port=0) as srv:
            url = f"http://127.0.0.1:{srv.port}/metrics"
            before = parse_prometheus(urllib.request.urlopen(url, timeout=5).read().decode())
            pm.observe_frame(1)
            after = parse_prometheus(urllib.request.urlopen(url, timeout=5).read().decode())
        assert before[("psana_ray_frames_total", 'source="p"')] == 0.0
        assert after[("psana_ray_frames_total", 'source="p"')] == 1.0

    def test_port_zero_is_off(self):
        # the CLI contract: --metrics_port 0 starts nothing (zero cost)
        assert start_metrics_server(0) is None
        assert start_metrics_server(-1) is None
        assert start_metrics_server(None) is None


class TestQueueStatsRPC:
    def test_ring_stats_fields(self):
        q = RingBuffer(4)
        from psana_ray_tpu.records import FrameRecord

        rec = FrameRecord(0, 0, np.zeros((1, 4, 4), np.float32), 1.0)
        assert q.put(rec)
        assert q.put(rec)
        q.get()
        s = q.stats()
        assert s["depth"] == 1
        assert s["puts"] == 2
        assert s["gets"] == 1
        assert s["high_water"] == 2
        assert s["maxsize"] == 4
        assert 0 <= s["last_put_age_s"] < 60
        assert 0 <= s["last_get_age_s"] < 60
        assert s["closed"] is False

    def test_tcp_stats_opcode(self):
        """Queue-health RPC ('T'): a remote client reads the same stats
        dict the server-side ring reports."""
        from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer

        srv = TcpQueueServer(RingBuffer(8), host="127.0.0.1", maxsize=8).serve_background()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            try:
                assert c.put(b"abc")
                s = c.stats()
                assert s["depth"] == 1
                assert s["puts"] == 1
                assert s["high_water"] == 1
                assert s["maxsize"] == 8
            finally:
                c.disconnect()
            # server-side aggregation used by --metrics_port on the server
            labels = srv.stats_all()
            assert labels["default"]["depth"] == 1
        finally:
            srv.shutdown()


class _FakeQueue:
    """stats()-bearing stub whose counters the test scripts directly."""

    def __init__(self, depth=0, maxsize=4, puts=0, gets=0):
        self.d = {"depth": depth, "maxsize": maxsize, "puts": puts, "gets": gets}

    def stats(self):
        return dict(self.d)


class TestStallDetector:
    def test_backpressure_fires_once_and_rearms(self):
        q = _FakeQueue(depth=4, maxsize=4, puts=10, gets=6)
        det = StallDetector(full_threshold_s=5.0, idle_threshold_s=1e9)
        det.watch("epix", q)
        det.poll_once(now=100.0)
        assert not det.events  # below threshold
        det.poll_once(now=106.0)
        events = list(det.events)
        assert [e.kind for e in events] == [EVENT_BACKPRESSURE]
        assert events[0].queue == "epix"
        assert events[0].depth == 4 and events[0].maxsize == 4
        json.loads(events[0].to_json())  # structured contract
        # same episode: no duplicate warning
        det.poll_once(now=120.0)
        assert len(det.events) == 1
        # condition clears -> re-arms -> fires again on the next episode
        q.d["depth"] = 1
        det.poll_once(now=121.0)
        q.d["depth"] = 4
        det.poll_once(now=122.0)
        det.poll_once(now=128.0)
        assert [e.kind for e in det.events] == [EVENT_BACKPRESSURE] * 2
        assert det.snapshot()[f"{EVENT_BACKPRESSURE}_total"] == 2

    def test_consumer_stall_on_blocked_queue(self):
        """Acceptance: the detector fires on an artificially blocked queue
        (items sitting, no consumer progress)."""
        q = RingBuffer(2)
        from psana_ray_tpu.records import FrameRecord

        rec = FrameRecord(0, 0, np.zeros((1, 4, 4), np.float32), 1.0)
        assert q.put(rec) and q.put(rec)  # full, nobody reading
        fired = []
        det = StallDetector(
            full_threshold_s=5.0, idle_threshold_s=10.0, on_event=fired.append
        )
        det.watch("blocked", q)
        t0 = time.monotonic()
        det.poll_once(now=t0)          # baseline (counter deltas need one)
        det.poll_once(now=t0 + 6.0)    # backpressure threshold crossed;
        # the frozen-gets episode starts HERE (first poll where the get
        # counter is observably unchanged)
        det.poll_once(now=t0 + 17.0)   # idle threshold crossed too
        kinds = {e.kind for e in fired}
        assert kinds == {EVENT_BACKPRESSURE, EVENT_CONSUMER_STALL}
        snap = det.snapshot()
        assert snap[f"{EVENT_BACKPRESSURE}_total"] == 1
        assert snap[f"{EVENT_CONSUMER_STALL}_total"] == 1

    def test_producer_idle(self):
        q = _FakeQueue(depth=0, maxsize=4, puts=7, gets=7)
        det = StallDetector(idle_threshold_s=10.0)
        det.watch("starved", q)
        det.poll_once(now=50.0)  # baseline
        det.poll_once(now=51.0)  # frozen-puts episode starts here
        det.poll_once(now=62.0)
        assert [e.kind for e in det.events] == [EVENT_PRODUCER_IDLE]
        # progress resumes -> clears
        q.d["puts"] = 8
        q.d["depth"] = 1
        q.d["gets"] = 8
        q.d["depth"] = 0
        det.poll_once(now=62.0)
        assert len(det.events) == 1

    def test_healthy_queue_stays_quiet_and_rates(self):
        q = _FakeQueue(depth=1, maxsize=4, puts=0, gets=0)
        det = StallDetector(full_threshold_s=1.0, idle_threshold_s=2.0)
        det.watch("ok", q)
        for i in range(10):
            q.d["puts"] += 10
            q.d["gets"] += 10
            det.poll_once(now=100.0 + i)
        assert not det.events
        assert det.snapshot()["ok"]["put_rate"] == pytest.approx(10.0)
        assert det.snapshot()["ok"]["get_rate"] == pytest.approx(10.0)

    def test_dynamic_provider_and_registry_source(self):
        det = StallDetector(full_threshold_s=1.0)
        det.watch_provider(lambda: {"late": _FakeQueue(depth=4, maxsize=4)})
        det.poll_once(now=10.0)
        det.poll_once(now=12.0)
        assert [e.kind for e in det.events] == [EVENT_BACKPRESSURE]
        reg = MetricsRegistry()
        reg.register("stalls", det)
        samples = parse_prometheus(reg.render_prometheus())
        assert samples[("psana_ray_backpressure_total", 'source="stalls"')] == 1.0

    def test_background_thread_lifecycle(self):
        q = _FakeQueue(depth=4, maxsize=4)
        det = StallDetector(poll_interval_s=0.01, full_threshold_s=0.02)
        det.watch("bg", q)
        with det:
            deadline = time.monotonic() + 5.0
            while not det.events and time.monotonic() < deadline:
                time.sleep(0.01)
        assert [e.kind for e in det.events] == [EVENT_BACKPRESSURE]


class TestLatencyStatsSatellite:
    """Satellite: quantile caching — correct across interleaved observes,
    and summary_ms costs one sort, not three."""

    def test_quantiles_correct_after_cache_invalidation(self):
        ls = LatencyStats()
        for v in (0.005, 0.001, 0.003):
            ls.observe(v)
        assert ls.quantile(0.5) == 0.003
        ls.observe(0.002)  # invalidates the cached sort
        assert ls.quantile(0.5) == 0.003
        assert ls.quantile(0.0) == 0.001
        assert ls.quantile(0.99) == 0.005
        s = ls.summary_ms()
        assert s["p50_ms"] == pytest.approx(3.0)
        assert s["p99_ms"] == pytest.approx(5.0)

    def test_summary_sorts_once(self):
        ls = LatencyStats()
        for v in range(100):
            ls.observe(v / 1000.0)
        calls = {"n": 0}
        orig = sorted

        def counting_sorted(x):
            calls["n"] += 1
            return orig(x)

        import builtins

        try:
            builtins.sorted = counting_sorted
            ls.summary_ms()
            ls.summary_ms()  # cached: no further sort
        finally:
            builtins.sorted = orig
        assert calls["n"] == 1

    def test_mean_is_lifetime_not_reservoir(self):
        ls = LatencyStats(reservoir_size=4, seed=1)
        for v in range(100):
            ls.observe(float(v))
        assert ls.count == 100
        assert ls.mean == pytest.approx(np.mean(np.arange(100.0)))
        snap = ls.snapshot()
        assert snap["count"] == 100
        assert snap["mean_ms"] == pytest.approx(ls.mean * 1e3)

    def test_empty_snapshot_has_no_nan(self):
        snap = LatencyStats().snapshot()
        assert snap == {"count": 0}
        assert np.isnan(LatencyStats().quantile(0.5))


class TestConsumerHeartbeatFlag:
    def test_consumer_cli_takes_status_interval_and_metrics_port(self):
        """Satellite: the flags parse (the heartbeat behavior itself is
        covered by the e2e stage-timing test via PipelineMetrics)."""
        from psana_ray_tpu import consumer

        # smoke: main's parser accepts the flags without hitting transport
        with pytest.raises(SystemExit) as e:
            consumer.main(["--help"])
        assert e.value.code == 0

    def test_status_line_includes_queue_depth(self):
        pm = PipelineMetrics(queue=RingBuffer(4))
        line = pm.status_line()
        assert "depth" in line


class TestMetricsServerConcurrency:
    def test_parallel_scrapes(self):
        reg = MetricsRegistry()
        pm = PipelineMetrics()
        reg.register("p", pm)
        errors = []

        def scrape(url):
            try:
                for _ in range(5):
                    parse_prometheus(
                        urllib.request.urlopen(url, timeout=5).read().decode()
                    )
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        with MetricsServer(registry=reg, host="127.0.0.1", port=0) as srv:
            url = f"http://127.0.0.1:{srv.port}/metrics"
            threads = [threading.Thread(target=scrape, args=(url,)) for _ in range(4)]
            for t in threads:
                t.start()
            for _ in range(50):
                pm.observe_frame(1)
            for t in threads:
                t.join()
        assert not errors


class TestMultihostLegRegistration:
    def test_legs_register_under_detector_names(self):
        """MultiDetectorGlobalConsumer puts every leg on the process
        metrics endpoint: explicit obs_name wins, unnamed legs get their
        detector key."""
        jax = pytest.importorskip("jax")
        from jax.sharding import Mesh

        from psana_ray_tpu.infeed.multihost import (
            GlobalStreamConsumer,
            MultiDetectorGlobalConsumer,
        )
        from psana_ray_tpu.obs import MetricsRegistry

        mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
        named = GlobalStreamConsumer(
            RingBuffer(maxsize=4), local_batch_size=2, mesh=mesh,
            frame_shape=(1, 4, 4), obs_name="epix_custom",
        )
        unnamed = GlobalStreamConsumer(
            RingBuffer(maxsize=4), local_batch_size=2, mesh=mesh,
            frame_shape=(1, 4, 4),
        )
        MultiDetectorGlobalConsumer({"epix": named, "jungfrau": unnamed})
        sources = MetricsRegistry.default().sources()
        assert "multihost.epix_custom" in sources  # explicit name kept
        assert "multihost.epix" not in sources  # not double-registered
        assert "multihost.jungfrau" in sources  # auto-named by detector
        assert unnamed.obs_name == "jungfrau"
