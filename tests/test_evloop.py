"""Event-loop queue server (ISSUE 6): connection scaling with O(1)
threads, admission control, bounded waits as timer state, and
crash-redelivery. (The legacy thread-per-connection mode is removed —
ISSUE 7; its unique redelivery/admission coverage is folded in here.)

The C10K-style scaling tests drive raw streamed-subscriber sockets off
one client-side selector (a full TcpQueueClient per subscriber would
measure client-object overhead, not the server): each subscriber speaks
exactly the wire protocol — 'M' subscribe, push frames, cumulative 'K'
acks, final 'F'.
"""

import selectors
import socket
import struct
import threading
import time

import pytest

from psana_ray_tpu.records import FrameRecord
from psana_ray_tpu.transport import EMPTY, TransportClosed
from psana_ray_tpu.transport.codec import decode_payload
from psana_ray_tpu.transport.evloop import EVLOOP
from psana_ray_tpu.transport.ring import RingBuffer
from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer


def _mk(maxsize=256, **kw):
    q = RingBuffer(maxsize)
    srv = TcpQueueServer(q, host="127.0.0.1", **kw).serve_background()
    return q, srv


class SubscriberFleet:
    """N raw streamed subscribers multiplexed on one client-side
    selector; parses the push framing (status + seq:u64 + len:u32 +
    payload) and acks cumulatively as it consumes."""

    def __init__(self, port, n, window=8):
        self.sel = selectors.DefaultSelector()
        self.states = []
        for _ in range(n):
            s = socket.create_connection(("127.0.0.1", port), timeout=10.0)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.sendall(b"M" + struct.pack("<I", window))
            s.setblocking(False)
            st = {"sock": s, "buf": bytearray(), "delivered": 0, "closed": False}
            self.sel.register(s, selectors.EVENT_READ, st)
            self.states.append(st)

    def drain(self, total, timeout=60.0, decode=True):
        """Read until ``total`` frames arrived fleet-wide (or timeout);
        returns the decoded items."""
        out = []
        deadline = time.monotonic() + timeout
        while len(out) < total and time.monotonic() < deadline:
            for key, _ in self.sel.select(timeout=0.25):
                st = key.data
                s = st["sock"]
                try:
                    data = s.recv(1 << 16)
                except (BlockingIOError, InterruptedError):
                    continue
                if not data:
                    st["closed"] = True
                    self.sel.unregister(s)
                    continue
                st["buf"] += data
                if self._parse(st, out, decode):
                    s.sendall(b"K" + struct.pack("<Q", st["delivered"]))
        return out

    @staticmethod
    def _parse(st, out, decode):
        buf = st["buf"]
        n_new = 0
        while buf:
            if buf[0:1] == b"X":
                st["closed"] = True
                del buf[:1]
                continue
            assert buf[0:1] == b"1", f"unexpected status {buf[0:1]!r}"
            if len(buf) < 13:
                break
            seq, ln = struct.unpack_from("<QI", buf, 1)
            if len(buf) < 13 + ln:
                break
            payload = bytes(buf[13 : 13 + ln])
            out.append(decode_payload(payload) if decode else None)
            st["delivered"] = seq
            del buf[: 13 + ln]
            n_new += 1
        return n_new

    def close(self, clean=True):
        for st in self.states:
            s = st["sock"]
            try:
                if clean and not st["closed"]:
                    s.setblocking(True)
                    s.sendall(
                        b"K" + struct.pack("<Q", st["delivered"]) + b"F"
                    )
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        self.sel.close()


def _rss_kb():
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    return 0


class TestEventLoopBasics:
    def test_evloop_is_the_default_mode(self):
        q, srv = _mk()
        try:
            assert srv.mode == "evloop"
            assert srv._loop is not None
        finally:
            srv.shutdown()

    def test_threads_mode_is_removed(self):
        # ISSUE 7 satellite: the legacy thread-per-connection mode was
        # scheduled for deletion one release after the event loop became
        # the default — asking for it must fail loudly, not silently
        # fall back (its unique coverage lives in the suites below now)
        with pytest.raises(ValueError, match="threads"):
            TcpQueueServer(RingBuffer(4), host="127.0.0.1", mode="threads")

    def test_bounded_wait_is_timer_state_not_a_thread(self):
        """'D' against an empty queue must honor its deadline through the
        timer heap, and wake promptly when another TCP client enqueues
        (in-loop wake, no poll tick on the wire)."""
        q, srv = _mk()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            t0 = time.monotonic()
            assert c.get_batch(4, timeout=0.3) == []
            assert time.monotonic() - t0 >= 0.25
            prod = TcpQueueClient("127.0.0.1", srv.port)
            threading.Timer(0.15, lambda: prod.put({"i": 1})).start()
            t0 = time.monotonic()
            out = c.get_batch(4, timeout=5.0)
            assert out == [{"i": 1}]
            assert time.monotonic() - t0 < 1.0  # woken, not expired
            prod.disconnect()
            c.disconnect()
        finally:
            srv.shutdown()

    def test_in_process_put_wakes_waiter_via_listener(self):
        """A direct RingBuffer.put from another thread must reach a
        parked 'D' waiter through the change listener + waker pipe."""
        q, srv = _mk()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            threading.Timer(0.15, lambda: q.put({"j": 2})).start()
            t0 = time.monotonic()
            out = c.get_batch(4, timeout=5.0)
            assert out == [{"j": 2}]
            assert time.monotonic() - t0 < 1.0
            c.disconnect()
        finally:
            srv.shutdown()


class TestAdmissionControl:
    def test_max_conns_refuses_with_protocol_error(self):
        q, srv = _mk(max_conns=2)
        try:
            refused0 = EVLOOP.stats()["refused_total"]
            c1 = TcpQueueClient("127.0.0.1", srv.port)
            c2 = TcpQueueClient("127.0.0.1", srv.port)
            assert c1.put({"a": 1}) and c2.size() == 1  # both admitted
            c3 = TcpQueueClient("127.0.0.1", srv.port, reconnect_tries=1,
                                reconnect_base_s=0.01)
            with pytest.raises((RuntimeError, TransportClosed)):
                c3.size()  # the refusal 'E' surfaces on first use
            assert EVLOOP.stats()["refused_total"] > refused0
            # admitted clients keep working through the refusal
            assert c2.get() == {"a": 1}
            c1.disconnect()
            c2.disconnect()
        finally:
            srv.shutdown()

    def test_slots_free_after_disconnect(self):
        q, srv = _mk(max_conns=1)
        try:
            c1 = TcpQueueClient("127.0.0.1", srv.port)
            assert c1.size() == 0
            c1.disconnect()
            deadline = time.monotonic() + 5.0
            # the slot frees once the server observes the close
            while time.monotonic() < deadline:
                c2 = TcpQueueClient("127.0.0.1", srv.port)
                try:
                    assert c2.size() == 0
                    break
                except RuntimeError:
                    c2.disconnect()
                    time.sleep(0.05)
            else:
                pytest.fail("slot never freed after clean disconnect")
            c2.disconnect()
        finally:
            srv.shutdown()


class TestRedelivery:
    """The at-least-once contract (formerly pinned across BOTH server
    modes; the threads mode is gone and this is its folded-in unique
    coverage): kill a streaming consumer mid-window and exactly the
    unacked tail redelivers."""

    def test_kill_after_partial_ack_redelivers_exactly_the_tail(self):
        import numpy as np

        q, srv = _mk(maxsize=64)
        try:
            for i in range(10):
                q.put(FrameRecord(0, i, np.full((1, 8, 8), float(i), np.float32), 1.0))
            c = TcpQueueClient("127.0.0.1", srv.port)
            c.stream_open(window=32)
            first = []
            deadline = time.monotonic() + 5.0
            while len(first) < 6 and time.monotonic() < deadline:
                first.extend(c.get_batch_stream(6 - len(first), timeout=1.0))
            assert len(first) == 6
            from psana_ray_tpu.transport.tcp import STREAM

            inflight_before_ack = STREAM.stats()["inflight"]
            # coming back acks the previous six
            second = []
            while not second and time.monotonic() < deadline:
                second = c.get_batch_stream(1, timeout=1.0)
            assert len(second) == 1 and second[0].event_idx == 6
            # wait until the SERVER has processed the cumulative ack
            # for 0..5 before killing the socket: closing with unread
            # pushes in the client's receive buffer sends RST, which
            # can flush the in-flight 'K' out of the server's receive
            # queue — then ALL ten frames redeliver and the exact-tail
            # assertion flakes under CPU load (measured 1/10 on a
            # loaded box). The server-side prune drops inflight by 6.
            ack_deadline = time.monotonic() + 5.0
            while (
                STREAM.stats()["inflight"] > inflight_before_ack - 6
                and time.monotonic() < ack_deadline
            ):
                time.sleep(0.01)
            c._sock.close()  # crash with seq 7..10 un-ACKed
            deadline = time.monotonic() + 5.0
            while q.size() < 4 and time.monotonic() < deadline:
                time.sleep(0.01)
            redelivered = sorted(
                r.event_idx for r in [q.get() for _ in range(q.size())]
            )
            # 0..5 acked (never redelivered); 6 delivered-but-unacked
            # (duplicate); 7..9 undelivered
            assert redelivered == [6, 7, 8, 9]
        finally:
            srv.shutdown()

    def test_unacked_get_requeues_on_death(self):
        q, srv = _mk(maxsize=8)
        try:
            q.put({"k": 5})
            c = TcpQueueClient("127.0.0.1", srv.port)
            assert c.get() == {"k": 5} and q.size() == 0
            c._sock.close()  # no next request, no BYE
            deadline = time.monotonic() + 5.0
            while q.size() == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert q.size() == 1 and q.get() == {"k": 5}
        finally:
            srv.shutdown()


class TestConnectionScaling:
    """Tier-1 smoke: >=200 concurrent streamed subscribers on loopback,
    every frame delivered exactly once (no crashes -> no duplicates per
    the at-least-once contract), server thread count O(1)."""

    N_SUBS = 200
    N_FRAMES = 600

    def test_200_streamed_subscribers_exactly_once_O1_threads(self):
        q, srv = _mk(maxsize=256)
        fleet = None
        prod = None
        try:
            threads_before = threading.active_count()
            fleet = SubscriberFleet(srv.port, self.N_SUBS, window=8)
            # 200 live connections added ZERO server threads (the loop
            # thread already existed) — the whole point of the rewrite
            assert threading.active_count() == threads_before
            prod = TcpQueueClient("127.0.0.1", srv.port)

            def produce():
                for i in range(self.N_FRAMES):
                    assert prod.put_wait({"i": i}, timeout=60.0)

            t = threading.Thread(target=produce, daemon=True)
            t.start()
            items = fleet.drain(self.N_FRAMES, timeout=90.0)
            t.join(timeout=10.0)
            assert len(items) == self.N_FRAMES
            # exactly once: all present, none duplicated
            assert sorted(d["i"] for d in items) == list(range(self.N_FRAMES))
            assert threading.active_count() == threads_before
        finally:
            if fleet is not None:
                fleet.close()
            if prod is not None:
                prod.disconnect()
            srv.shutdown()

    @pytest.mark.slow
    def test_1000_subscribers_no_collapse_flat_memory(self):
        """ISSUE 6 acceptance shape (the judged numbers live in the
        bench row): 1000 concurrent streamed subscribers deliver every
        frame exactly once, per-connection RSS growth stays under 64 KB,
        thread count stays flat, and throughput does not collapse
        relative to a 16-subscriber run on the same server config."""
        n_frames = 3000

        def run(n_subs):
            q, srv = _mk(maxsize=512)
            fleet = prod = None
            try:
                rss0 = _rss_kb()
                fleet = SubscriberFleet(srv.port, n_subs, window=8)
                rss_per_conn_kb = (_rss_kb() - rss0) / n_subs
                prod = TcpQueueClient("127.0.0.1", srv.port)
                threads0 = threading.active_count()

                def produce():
                    for i in range(n_frames):
                        assert prod.put_wait({"i": i}, timeout=120.0)

                t = threading.Thread(target=produce, daemon=True)
                t0 = time.monotonic()
                t.start()
                items = fleet.drain(n_frames, timeout=240.0)
                dt = time.monotonic() - t0
                t.join(timeout=10.0)
                assert sorted(d["i"] for d in items) == list(range(n_frames))
                assert threading.active_count() == threads0
                return n_frames / dt, rss_per_conn_kb
            finally:
                if fleet is not None:
                    fleet.close()
                if prod is not None:
                    prod.disconnect()
                srv.shutdown()

        fps_16, _ = run(16)
        fps_1000, rss_per_conn = run(1000)
        assert rss_per_conn <= 64.0, (
            f"per-connection RSS growth {rss_per_conn:.1f} KB > 64 KB"
        )
        # no-collapse: generous floor for a noisy shared 2-core box; the
        # bench row records the honest ratio (acceptance: >=0.8 there)
        assert fps_1000 >= 0.5 * fps_16, (
            f"fps collapsed: {fps_1000:.0f} at 1000 subs vs {fps_16:.0f} at 16"
        )


class TestParkedLiveness:
    def test_dead_client_while_parked_no_pipelined_bytes_drops_frame(self):
        """EOF detection while a 'W' enqueue is parked (no pipelined
        bytes): the event loop keeps read interest armed and kills the
        connection the moment the peer closes — the parked frame is
        dropped, never enqueued late (the windowed-put resend covers it
        on a real reconnect). Parity with the threaded _peer_hung_up."""
        import struct as _struct

        from psana_ray_tpu.transport.codec import encode_payload

        q, srv = _mk(maxsize=1)
        try:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5.0)

            def w(seq, obj):
                payload = encode_payload(obj)
                s.sendall(
                    b"W" + _struct.pack("<QI", seq, len(payload)) + payload
                )

            w(1, {"i": 1})  # fills the queue (ack written, never read)
            w(2, {"i": 2})  # parks server-side: queue full
            time.sleep(0.3)
            s.close()  # dies mid-wait, nothing further pipelined
            time.sleep(0.6)
            assert q.get() == {"i": 1}  # frees the slot
            assert q.get_wait(timeout=1.0) is EMPTY  # frame 2 dropped
        finally:
            srv.shutdown()

    def test_dead_pipelining_producer_reaped_not_pinned(self):
        """Review fix (recurring liveness probe): a windowed producer
        that pipelines MORE requests and then dies while its enqueue is
        parked pauses the server's reads — the first MSG_PEEK pause must
        not end liveness checking forever. Contract parity with the
        threaded server (verified A/B): the parked frame may enqueue
        once space frees (an at-least-once DUPLICATE — its reconnect
        resend would carry it anyway; duplicates allowed, holes never),
        the never-read pipelined frame must NOT appear, and the dead
        connection is reaped — not pinned with its lease forever."""
        import struct as _struct

        from psana_ray_tpu.transport.codec import encode_payload

        q, srv = _mk(maxsize=1)
        try:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5.0)

            def w(seq, obj):
                payload = encode_payload(obj)
                s.sendall(
                    b"W" + _struct.pack("<QI", seq, len(payload)) + payload
                )

            w(1, {"i": 1})  # fills the queue
            w(2, {"i": 2})  # parks server-side: queue full
            w(3, {"i": 3})  # pipelined bytes -> server pauses reads
            time.sleep(0.4)
            conns_live = EVLOOP.stats()["connections"]
            s.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                _struct.pack("ii", 1, 0),
            )
            s.close()
            time.sleep(1.2)  # > 2 probe intervals
            assert q.get() == {"i": 1}  # frees the slot
            # frame 2 may arrive as a duplicate (same as threads mode);
            # frame 3 must never complete its read
            seen = []
            item = q.get_wait(timeout=2.0)
            while item is not EMPTY:
                seen.append(item)
                item = q.get_wait(timeout=0.5)
            assert {"i": 3} not in seen, seen
            # and the dead connection is reaped, not pinned: the write
            # of frame 2's ack (or the probe) discovers the death
            deadline = time.monotonic() + 5.0
            while (
                EVLOOP.stats()["connections"] >= conns_live
                and time.monotonic() < deadline
            ):
                time.sleep(0.05)
            assert EVLOOP.stats()["connections"] < conns_live
        finally:
            srv.shutdown()


class TestStreamFairness:
    def test_two_subscribers_share_one_queue_without_starvation(self):
        q, srv = _mk(maxsize=128)
        fleet = None
        try:
            fleet = SubscriberFleet(srv.port, 2, window=4)
            prod = TcpQueueClient("127.0.0.1", srv.port)
            for i in range(64):
                assert prod.put_wait({"i": i}, timeout=30.0)
            items = fleet.drain(64, timeout=30.0)
            assert sorted(d["i"] for d in items) == list(range(64))
            # round-robin pump: both connections actually got frames
            counts = [st["delivered"] for st in fleet.states]
            assert all(c > 0 for c in counts), counts
            prod.disconnect()
        finally:
            if fleet is not None:
                fleet.close()
            srv.shutdown()


class TestLoopTelemetry:
    def test_evloop_gauges_register_and_count(self):
        from psana_ray_tpu.obs.registry import snapshot_source

        q, srv = _mk()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            assert c.put({"x": 1}) and c.get() == {"x": 1}
            s = EVLOOP.stats()
            assert s["connections"] >= 1
            assert s["accepted_total"] >= 1
            assert s["loops_total"] >= 1
            # registry source protocol: the gauges scrape as a dict
            # (the loop registers itself as the 'evloop' source on the
            # process default registry at first start)
            snap = snapshot_source(EVLOOP)
            assert snap["connections_peak"] >= 1
            assert "dispatch_ms_max" in snap and "timer_lag_ms_max" in snap
            c.disconnect()
            deadline = time.monotonic() + 5.0
            while EVLOOP.stats()["connections"] > s["connections"] - 1 and \
                    time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            srv.shutdown()
