"""Equivalence tests for the fused Pallas ResNet inference path.

The fused kernels (models/pallas_resnet.py) must match the flax
``ResNetClassifier(norm='frozen')`` oracle to bfloat16 tolerance. On the
CPU test backend the kernels run in Pallas interpret mode — same math,
same masking/padding logic, no Mosaic lowering — which is the prescribed
way to unit-test TPU kernels off-hardware (pallas_guide).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from psana_ray_tpu.models.pallas_resnet import fused_bottleneck, resnet_fused_infer
from psana_ray_tpu.models.resnet import BottleneckBlock, ResNetClassifier


def _randomized(variables, key):
    """Perturb params so affine scales/biases are not init constants —
    otherwise scale=1/bias=0 would hide broadcast/transpose mistakes."""
    leaves, treedef = jax.tree.flatten(variables)
    keys = jax.random.split(key, len(leaves))
    out = [
        l + 0.1 * jax.random.normal(k, l.shape, l.dtype)
        if hasattr(l, "dtype") and l.dtype == jnp.float32
        else l
        for l, k in zip(leaves, keys)
    ]
    return jax.tree.unflatten(treedef, out)


def _rel_err(ref, got):
    """Max error normalized by the tensor's scale (elementwise relative
    error is meaningless on near-zero activations under bf16 rounding)."""
    ref = np.asarray(ref, np.float32)
    got = np.asarray(got, np.float32)
    return float(np.max(np.abs(ref - got)) / max(np.max(np.abs(ref)), 1e-3))


class TestFusedBottleneck:
    @pytest.mark.parametrize(
        "cin,f,stride,proj",
        [
            (64, 16, 1, False),   # identity block (cin == 4f)
            (32, 16, 1, True),    # projection, stride 1
            (64, 32, 2, True),    # projection + downsample
        ],
    )
    def test_matches_flax_block(self, rng, cin, f, stride, proj):
        self._check_block(rng, cin, f, stride, proj)

    @pytest.mark.parametrize(
        "cin,f,stride,proj",
        [
            (64, 16, 1, False),
            (64, 32, 2, True),
        ],
    )
    def test_split_path_matches_flax_block(self, rng, monkeypatch, cin, f, stride, proj):
        """Starve the VMEM budget so the block takes the two-kernel split
        path (front conv1+conv3x3 | back conv1x1+residual) — the route
        real stage-4 projection blocks compile through."""
        import psana_ray_tpu.models.pallas_resnet as pr

        monkeypatch.setattr(pr, "_VMEM_BUDGET", 1 << 20)
        self._check_block(rng, cin, f, stride, proj)

    def _check_block(self, rng, cin, f, stride, proj):
        h = w = 16
        block = BottleneckBlock(
            features=f, strides=(stride, stride), norm="frozen"
        )
        x = jnp.asarray(rng.normal(size=(2, h, w, cin)).astype(np.float32))
        variables = _randomized(block.init(jax.random.key(0), x), jax.random.key(1))
        assert ("proj" in variables["params"]) == proj
        ref = block.apply(variables, x)

        from flax.core import meta

        p = meta.unbox(variables)["params"]
        w1 = p["Conv_0"]["kernel"].astype(jnp.bfloat16).reshape(cin, f)
        w2 = p["Conv_1"]["kernel"].astype(jnp.bfloat16).reshape(9, f, f)
        w3 = p["Conv_2"]["kernel"].astype(jnp.bfloat16).reshape(f, 4 * f)
        aff = []
        for name in ("FrozenAffine_0", "FrozenAffine_1", "FrozenAffine_2"):
            ap = p[name]
            ch = ap["scale"].shape[0]
            aff += [
                ap["scale"].astype(jnp.float32).reshape(1, ch),
                ap["bias"].astype(jnp.float32).reshape(1, ch),
            ]
        wp = None
        if proj:
            wp = p["proj"]["kernel"].astype(jnp.bfloat16).reshape(cin, 4 * f)
            aff += [
                p["proj_norm"]["scale"].astype(jnp.float32).reshape(1, 4 * f),
                p["proj_norm"]["bias"].astype(jnp.float32).reshape(1, 4 * f),
            ]

        got = fused_bottleneck(
            x.astype(jnp.bfloat16), w1, w2, w3, tuple(aff), wp=wp,
            stride=stride, interpret=True,
        )
        assert got.shape == ref.shape
        assert _rel_err(ref, got) < 0.05  # bf16 taps + f32 accumulation

    def test_unaligned_width_padding_is_exact(self, rng):
        """w_true < padded buffer width: padded columns must stay zero and
        not leak into 3x3 taps or the residual."""
        cin, f, h, w_true = 64, 16, 16, 12  # buffer width padded to 16
        block = BottleneckBlock(features=f, strides=(1, 1), norm="frozen")
        x = jnp.asarray(rng.normal(size=(2, h, w_true, cin)).astype(np.float32))
        variables = _randomized(block.init(jax.random.key(0), x), jax.random.key(1))
        ref = block.apply(variables, x)

        from flax.core import meta

        from psana_ray_tpu.models.pallas_resnet import _block_params, _pad_to, _up

        w1, w2, w3, aff, wp = _block_params(meta.unbox(variables)["params"])
        xpad = _pad_to(x.astype(jnp.bfloat16), 2, _up(w_true, 8))
        got = fused_bottleneck(
            xpad, w1, w2, w3, aff, wp=wp, stride=1, w_true=w_true, interpret=True
        )
        assert _rel_err(ref, got[:, :, :w_true]) < 0.05
        np.testing.assert_array_equal(np.asarray(got[:, :, w_true:]), 0.0)


class TestResNetFusedInfer:
    def test_matches_flax_resnet(self, rng):
        stage_sizes = (1, 1)
        model = ResNetClassifier(
            stage_sizes=stage_sizes, num_classes=2, width=8, norm="frozen"
        )
        x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)).astype(np.float32))
        variables = _randomized(
            model.init(jax.random.key(0), x), jax.random.key(1)
        )
        ref = model.apply(variables, x)
        got = resnet_fused_infer(variables, x, stage_sizes=stage_sizes, interpret=True)
        assert got.shape == ref.shape
        assert _rel_err(ref, got) < 0.05

    def test_unaligned_input_width(self, rng):
        """Input width whose post-stem extent is not a multiple of 8."""
        stage_sizes = (1, 1)
        model = ResNetClassifier(
            stage_sizes=stage_sizes, num_classes=2, width=8, norm="frozen"
        )
        x = jnp.asarray(rng.normal(size=(1, 48, 40, 2)).astype(np.float32))
        variables = _randomized(
            model.init(jax.random.key(0), x), jax.random.key(1)
        )
        ref = model.apply(variables, x)
        got = resnet_fused_infer(variables, x, stage_sizes=stage_sizes, interpret=True)
        assert _rel_err(ref, got) < 0.05


def test_small_extent_falls_back_to_flax():
    """Inputs too small for the fused stage pipeline (deep stages would
    degenerate to 0 rows) must run the plain flax forward, not crash in a
    kernel slice — the bench smoke geometry (16x128) hit exactly this."""
    from psana_ray_tpu.models import ResNet50, host_init, panels_to_nhwc
    from psana_ray_tpu.models.pallas_resnet import resnet_fused_infer

    model = ResNet50(num_classes=2, norm="frozen")
    v = host_init(model, (1, 16, 128, 2))
    x = jnp.ones((3, 2, 16, 128))  # [B, panels, H, W]
    out = resnet_fused_infer(v, panels_to_nhwc(x))
    ref = model.apply(v, panels_to_nhwc(x))
    assert out.shape == (3, 2)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), rtol=1e-5
    )
