"""Tier-1 driver for the autotune subsystem (ISSUE 15).

Layers:

1. knob registry mechanics — bounds/quantum clamping, live setter
   round-trips, manual pins, the gateway single-writer rule;
2. the hill climber on a SYNTHETIC metric surface, driven tick by tick
   with explicitly-timed store samples — deterministic, no wall-clock;
3. guardrail semantics — an injected shed-rate spike reverts the open
   probe immediately and freezes probing for the episode;
4. observe mode actuates NOTHING (decisions are logged, setters never
   called);
5. live transport knobs — put/stream-window resize and codec
   renegotiation over a real event-loop server, plus the
   ``--wire_codec auto`` probe decision both ways (thresholds forced
   through the env override, no link shaping needed);
6. the ``autotune`` telemetry source shape and the CLI plumb;
7. the zero-copy pins (copies/frame 1.00, pool churn 0) with a LIVE
   controller actuating drain knobs mid-stream.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from psana_ray_tpu.autotune.controller import (
    Guardrail,
    HillClimber,
    Objective,
    default_guardrails,
)
from psana_ray_tpu.autotune.daemon import (
    AutotuneDaemon,
    add_autotune_args,
    configure_autotune_from_args,
)
from psana_ray_tpu.autotune.knobs import (
    GROUP_SERVING,
    Knob,
    KnobRegistry,
    bufpool_retention_knob,
    drain_chunk_knob,
    drain_poll_knob,
    fsync_batch_knob,
    prefetch_depth_knob,
    put_window_knob,
    ram_items_knob,
    stream_window_knob,
    wire_codec_knob,
)
from psana_ray_tpu.infeed.batcher import DrainControl, batches_from_queue
from psana_ray_tpu.obs.flight import FLIGHT
from psana_ray_tpu.obs.timeseries import TimeSeriesStore
from psana_ray_tpu.records import EndOfStream, FrameRecord
from psana_ray_tpu.transport.ring import RingBuffer
from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer
from psana_ray_tpu.utils.bufpool import WIRE, BufferPool


def _rec(i, shape=(2, 16, 16)):
    return FrameRecord(0, i, np.full(shape, i % 251, np.uint16), 9.5)


def _flight_since(n0, kind):
    """Events of ``kind`` recorded after lifetime-count ``n0`` (marks
    are ``FLIGHT.count_of(kind)``) — robust to ring eviction, unlike
    slicing ``events()`` by the lifetime event_count."""
    evs = [e for e in FLIGHT.events() if e["kind"] == kind]
    new = FLIGHT.count_of(kind) - n0
    return evs[-new:] if new > 0 else []


# ---------------------------------------------------------------------------
# 1. knob + registry mechanics
# ---------------------------------------------------------------------------

class TestKnobRegistry:
    def _val_knob(self, val, name="k", group="g", lo=1, hi=64, step=4):
        return Knob(
            name, group, "client", lo=lo, hi=hi, step=step,
            get=lambda: val[0], set=lambda v: val.__setitem__(0, v),
        )

    def test_clamp_quantizes_to_the_step_grid(self):
        k = self._val_knob([8.0])
        assert k.clamp(0) == 1
        assert k.clamp(999) == 64
        assert k.clamp(10.9) == 9  # grid anchored at lo: 1, 5, 9, ...
        assert k.clamp(11.1) == 13
        assert k.neighbor(9, +1) == 13
        assert k.neighbor(1, -1) == 1  # pinned at the bound

    def test_discrete_menu_snaps_and_steps(self):
        val = [1.0]
        k = Knob(
            "codec", "codec", "client", lo=0, hi=1, step=1,
            get=lambda: val[0], set=lambda v: val.__setitem__(0, v),
            values=(0.0, 1.0),
        )
        assert k.clamp(0.7) == 1.0
        assert k.neighbor(1.0, -1) == 0.0
        assert k.neighbor(1.0, +1) == 1.0

    def test_apply_round_trips_through_the_setter(self):
        val = [8.0]
        reg = KnobRegistry()
        reg.register(self._val_knob(val))
        mark = FLIGHT.event_count
        out = reg.apply("k", 14.0)  # quantized to the grid
        assert out == 13 and val[0] == 13
        assert reg.current("k") == 13
        evs = [e for e in FLIGHT.events() if e["kind"] == "autotune_actuate"]
        assert evs and evs[-1]["knob"] == "k" and evs[-1]["to"] == 13
        assert FLIGHT.event_count > mark  # never silent

    def test_pinned_and_excluded_knobs_leave_the_rotation(self):
        reg = KnobRegistry()
        reg.register(self._val_knob([1.0], name="a", group="g1"))
        reg.register(self._val_knob([1.0], name="b", group="g2"))
        reg.register(self._val_knob([1.0], name="c", group=GROUP_SERVING))
        assert reg.eligible() == ["a", "b", "c"]
        reg.pin("a", "--flag set explicitly")
        reg.note_gateway(object())
        assert reg.eligible() == ["b"]
        snap = reg.snapshot()
        assert snap["a"]["pinned"] == 1 and snap["pinned_total"] == 1

    def test_duplicate_registration_refused_and_none_absorbed(self):
        reg = KnobRegistry()
        reg.register(self._val_knob([1.0]))
        assert reg.register(None) is None
        with pytest.raises(ValueError, match="already registered"):
            reg.register(self._val_knob([1.0]))

    def test_observe_mode_never_calls_the_setter(self):
        calls = []
        reg = KnobRegistry(mode="observe")
        reg.register(Knob(
            "k", "g", "client", lo=1, hi=64, step=4,
            get=lambda: 8.0, set=lambda v: calls.append(v),
        ))
        mark = FLIGHT.event_count
        out = reg.apply("k", 12.0)
        assert out == 8.0 and not calls
        obs = _flight_since(0, "autotune_observe")
        assert obs and obs[-1]["would_set"] == 13.0
        assert reg.snapshot()["observed_total"] == 1
        assert FLIGHT.event_count > mark


# ---------------------------------------------------------------------------
# 2-4. the hill climber: convergence, guardrails, observe mode —
# all tick-driven over explicitly-timed synthetic samples
# ---------------------------------------------------------------------------

def _drive(hc, store, val, f, ticks, t0=1000.0, counters=None):
    """Feed one sample per second of FAKE time, tick after each. ``f``
    maps knob value -> instantaneous fps. ``counters`` adds extra
    monotone keys (guardrail counters)."""
    # per-store cumulative counter state so callers can drive in stages
    if not hasattr(store, "_test_cum"):
        store._test_cum = {"fps": 0.0, "t": t0}
    cum = store._test_cum
    for _ in range(ticks):
        cum["fps"] += f(val[0])
        cum["t"] += 1.0
        tree = {"syn": {"frames_total": cum["fps"]}}
        if counters:
            tree.update(counters(cum["t"]))
        store.record(tree, now=cum["t"])
        hc.tick()


class TestHillClimber:
    def _setup(self, start=8.0, guardrails=(), mode="on", **kw):
        store = TimeSeriesStore()
        reg = KnobRegistry(mode=mode)
        val = [start]
        reg.register(Knob(
            "k", "g", "client", lo=1, hi=64, step=4,
            get=lambda: val[0], set=lambda v: val.__setitem__(0, v),
        ))
        kw.setdefault("hold_ticks", 2)
        kw.setdefault("settle_ticks", 3)
        kw.setdefault("cooldown_ticks", 2)
        hc = HillClimber(
            reg, Objective("syn.frames_total", window_s=2.5),
            store=store, guardrails=guardrails, **kw,
        )
        return store, reg, val, hc

    def test_converges_on_a_synthetic_surface_and_holds(self):
        """Deterministic convergence: fps peaks at k=33 (on the quantum
        grid); the climber must walk there and STAY (hysteresis: once
        converged, probes at the peak revert and the knob sits still)."""
        store, reg, val, hc = self._setup()
        _drive(hc, store, val, lambda k: 1000.0 - abs(k - 33.0) * 10.0, 400)
        assert abs(val[0] - 33.0) <= 4.0, val[0]
        # converged: further driving leaves it at the peak
        settled = val[0]
        _drive(hc, store, val, lambda k: 1000.0 - abs(k - 33.0) * 10.0, 80)
        assert abs(val[0] - settled) <= 4.0
        snap = reg.snapshot()
        assert snap["k"]["actuations_total"] > 0
        assert snap["k"]["kept_total"] > 0  # improvements held
        assert snap["k"]["reverts_total"] > 0  # the peak pushes back

    def test_regression_reverts_and_flips_direction(self):
        """On a monotone-DECREASING surface every upward probe is a
        regression: the knob must end at or below its start, and every
        probe must have a matching revert (never silently kept)."""
        store, reg, val, hc = self._setup(start=33.0)
        mark = FLIGHT.count_of("autotune_revert")
        _drive(hc, store, val, lambda k: 2000.0 - k * 10.0, 120)
        assert val[0] <= 33.0
        snap = reg.snapshot()["k"]
        reverts = _flight_since(mark, "autotune_revert")
        assert snap["reverts_total"] == len(reverts) > 0

    def test_guardrail_trip_reverts_the_open_probe(self):
        """An injected shed-rate spike mid-probe reverts IMMEDIATELY
        (not at the end of the hold window), breadcrumbs the trip, and
        freezes probing while the spike lasts."""
        shed_rate = [0.0]

        def counters(t):
            # a counter increasing at shed_rate/s
            c = getattr(counters, "cum", 0.0) + shed_rate[0]
            counters.cum = c
            return {"gateway": {"shed_total": c}}

        store, reg, val, hc = self._setup(
            guardrails=[Guardrail("gateway.shed_total", "rate_above", 1.0)],
        )
        f = lambda k: 1000.0 + k * 50.0  # noqa: E731 — upward probes improve
        _drive(hc, store, val, f, 12, counters=counters)
        probed = val[0]
        assert probed > 8.0  # a probe is open or was kept
        mark = FLIGHT.count_of("autotune_guardrail")
        acts = reg.snapshot()["k"]["actuations_total"]
        shed_rate[0] = 50.0  # spike
        _drive(hc, store, val, f, 20, counters=counters)
        trips = _flight_since(mark, "autotune_guardrail")
        assert trips, "guardrail trip must breadcrumb"
        # probing frozen during the episode: no NEW probes opened (the
        # only actuation allowed after the trip is the revert itself)
        after = reg.snapshot()["k"]
        assert after["actuations_total"] <= acts + 1
        assert hc.guardrail_trips > 0

    def test_observe_mode_logs_decisions_but_never_actuates(self):
        store, reg, val, hc = self._setup(mode="observe")
        mark_obs = FLIGHT.count_of("autotune_observe")
        mark_act = FLIGHT.count_of("autotune_actuate")
        _drive(hc, store, val, lambda k: 1000.0 + k, 60)
        assert val[0] == 8.0  # untouched
        obs = _flight_since(mark_obs, "autotune_observe")
        assert obs, "observe mode must log what it would do"
        assert not _flight_since(mark_act, "autotune_actuate")

    def test_starved_metrics_abort_an_open_probe(self):
        """A store with no fresh samples (objective returns None) must
        abort the probe within max_starved_ticks, restoring the saved
        value — never leave a half-probed knob in place forever."""
        store, reg, val, hc = self._setup(max_starved_ticks=3, settle_ticks=0)
        # the first tick's rate view is still empty (one sample), then
        # two baseline ticks, then the probe opens (hold_ticks=2)
        _drive(hc, store, val, lambda k: 1000.0, 3)
        assert val[0] == 13.0, "probe should be open at the stepped value"
        # starve the objective: swap in an EMPTY store
        hc._store = TimeSeriesStore()
        for _ in range(6):
            hc.tick()
        assert val[0] == 8.0, "probe must revert once metrics starve"


# ---------------------------------------------------------------------------
# single-writer rule: gateway-bound knobs defer to SloPolicy
# ---------------------------------------------------------------------------

class TestSingleWriterWithSloPolicy:
    def test_gateway_bound_serving_knobs_are_never_actuated(self):
        """ISSUE 15 satellite: bind BOTH a serving gateway (SloPolicy
        refining batch choice per dispatch) and an autotune registry
        holding a serving-group knob — the controller must never write
        the batch dial (single-writer), while SloPolicy keeps learning
        from dispatches."""
        from psana_ray_tpu.serving.gateway import ServingGateway
        from psana_ray_tpu.serving.policy import SloPolicy

        policy = SloPolicy(slo_ms=50.0)
        gw = ServingGateway(lambda recs, b: None, policy=policy)
        control = DrainControl(chunk=8, poll_s=0.01)
        set_calls = []
        store = TimeSeriesStore()
        reg = KnobRegistry()
        knob = drain_chunk_knob(control)
        knob.set = lambda v: set_calls.append(v)  # count actuations
        reg.register(knob)
        reg.note_gateway(gw)
        hc = HillClimber(
            reg, Objective("syn.frames_total", window_s=2.5),
            store=store, hold_ticks=2, settle_ticks=1,
        )
        val = [0.0]
        _drive(hc, store, val, lambda k: 1000.0, 60)
        assert not set_calls, "controller wrote a gateway-owned knob"
        assert reg.eligible() == []
        # SloPolicy remains the single writer of batch sizing
        before = policy.snapshot()["service_ms"]["8"]
        policy.observe_service(8, 99.0)
        assert policy.snapshot()["service_ms"]["8"] != before

    def test_without_a_gateway_the_same_knob_is_controlled(self):
        control = DrainControl(chunk=8, poll_s=0.01)
        store = TimeSeriesStore()
        reg = KnobRegistry()
        reg.register(drain_chunk_knob(control))
        hc = HillClimber(
            reg, Objective("syn.frames_total", window_s=2.5),
            store=store, hold_ticks=2, settle_ticks=1,
        )
        val = [0.0]
        _drive(hc, store, val, lambda k: 1000.0 + control.chunk, 40)
        snap = reg.snapshot()["drain_chunk"]
        assert snap["actuations_total"] > 0


# ---------------------------------------------------------------------------
# 5. live transport knobs over a real event-loop server
# ---------------------------------------------------------------------------

class TestLiveTransportKnobs:
    def test_put_window_and_stream_window_resize_live(self):
        srv = TcpQueueServer(RingBuffer(64), host="127.0.0.1").serve_background()
        c = TcpQueueClient("127.0.0.1", srv.port)
        try:
            c.set_put_window(7)
            assert c.put_window == 7
            c.stream_open(window=4)
            mark = FLIGHT.count_of("stream_resize")
            assert c.set_stream_window(48)
            assert c.stream_window == 48
            # the server observed the resize (breadcrumb from evloop)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if _flight_since(mark, "stream_resize"):
                    break
                time.sleep(0.01)
            evs = _flight_since(mark, "stream_resize")
            assert evs and evs[-1]["window"] == 48 and evs[-1]["old"] == 4
            # ...and the wider window actually carries more frames in
            # flight: push more than the OLD window without acking
            for i in range(12):
                assert c._side_channel().put_wait(_rec(i), timeout=10)
            got = c.get_batch_stream(12, timeout=10)
            deadline = time.monotonic() + 10
            while len(got) < 12 and time.monotonic() < deadline:
                got.extend(c.get_batch_stream(12 - len(got), timeout=0.5))
            assert len(got) == 12  # > the subscribe-time window of 4
            for r in got:
                release = getattr(r, "release", None)
                if release:
                    release()
        finally:
            c.disconnect()
            srv.shutdown()

    def test_stream_window_resize_refused_without_subscription(self):
        srv = TcpQueueServer(RingBuffer(4), host="127.0.0.1").serve_background()
        c = TcpQueueClient("127.0.0.1", srv.port)
        try:
            with pytest.raises(RuntimeError, match="stream subscription"):
                c.set_stream_window(16)
        finally:
            c.disconnect()
            srv.shutdown()

    def test_renegotiate_codec_flips_compression_live(self):
        srv = TcpQueueServer(RingBuffer(8), host="127.0.0.1").serve_background()
        c = TcpQueueClient("127.0.0.1", srv.port)
        try:
            assert c.codec_name is None
            assert c.renegotiate_codec(["shuffle-rle"])
            assert c.codec_name == "shuffle-rle"
            rec = _rec(1)
            assert c.put(rec)
            out = c.get()
            assert out.equals(rec)
            out.release()
            assert c.renegotiate_codec(None) is False
            assert c.codec_name is None
            assert c.put(rec)
            out = c.get()
            assert out.equals(rec)
            out.release()
        finally:
            c.disconnect()
            srv.shutdown()

    def test_knob_factories_wrap_the_real_client(self):
        srv = TcpQueueServer(RingBuffer(8), host="127.0.0.1").serve_background()
        c = TcpQueueClient("127.0.0.1", srv.port)
        try:
            k = put_window_knob(c)
            assert k is not None
            k.set(k.clamp(40))
            assert c.put_window == 40
            ck = wire_codec_knob(c)
            assert ck is not None and ck.get() == 0.0
            ck.set(1.0)
            assert ck.get() == 1.0 and c.codec_name is not None
            ck.set(0.0)
            assert ck.get() == 0.0
            # stream knob declines nothing (client supports it), but a
            # bare object without the surface is declined
            assert stream_window_knob(object()) is None
            assert put_window_knob(object()) is None
            assert wire_codec_knob(object()) is None
        finally:
            c.disconnect()
            srv.shutdown()


class TestAutoCodecDecision:
    """``--wire_codec auto`` (ISSUE 15 satellite): one-shot decision at
    connect from the link-rate probe, re-evaluated on reconnect,
    breadcrumbed — forced both ways via the env threshold override (no
    link shaping needed; the bench's A/B runs the real throttle)."""

    def test_fast_link_decides_off(self, monkeypatch):
        monkeypatch.setenv("PSANA_AUTO_CODEC_MB_S", "0.000001")
        srv = TcpQueueServer(RingBuffer(8), host="127.0.0.1").serve_background()
        mark = FLIGHT.count_of("codec_auto_decision")
        c = TcpQueueClient("127.0.0.1", srv.port, codec="auto")
        try:
            assert c.codec_name is None
            evs = _flight_since(mark, "codec_auto_decision")
            assert evs and evs[-1]["codec_on"] is False
            assert evs[-1]["link_mb_s"] is not None
            rec = _rec(2)
            assert c.put(rec)
            out = c.get()
            assert out.equals(rec)
            out.release()
        finally:
            c.disconnect()
            srv.shutdown()

    def test_slow_link_decides_on_and_reconnect_redecides(self, monkeypatch):
        monkeypatch.setenv("PSANA_AUTO_CODEC_MB_S", "1e9")
        srv = TcpQueueServer(RingBuffer(8), host="127.0.0.1").serve_background()
        mark = FLIGHT.count_of("codec_auto_decision")
        c = TcpQueueClient("127.0.0.1", srv.port, codec="auto")
        try:
            assert c.codec_name == "shuffle-rle"
            evs = _flight_since(mark, "codec_auto_decision")
            assert evs and evs[-1]["codec_on"] is True
            # the link "changes" (threshold flips): a reconnect must
            # RE-DECIDE, landing uncompressed this time
            monkeypatch.setenv("PSANA_AUTO_CODEC_MB_S", "0.000001")
            mark = FLIGHT.count_of("codec_auto_decision")
            c._sock.close()  # sever: next op reconnects
            rec = _rec(3)
            assert c.put(rec)
            evs = _flight_since(mark, "codec_auto_decision")
            assert evs and evs[-1]["codec_on"] is False
            assert c.codec_name is None
            out = c.get()
            assert out.equals(rec)
            out.release()
        finally:
            c.disconnect()
            srv.shutdown()

    def test_producer_cli_accepts_auto_with_autotune_off(self):
        """The CLI value works standalone: --wire_codec auto parses and
        rides the config regardless of --autotune (off by default)."""
        from psana_ray_tpu.producer import parse_arguments

        cfg, a = parse_arguments(["--wire_codec", "auto"])
        assert cfg.transport.wire_codec == "auto"
        assert a.autotune == "off"


# ---------------------------------------------------------------------------
# 6. telemetry source shape + CLI plumb
# ---------------------------------------------------------------------------

class TestTelemetryAndCli:
    def test_autotune_source_shape(self):
        reg = KnobRegistry()
        val = [8.0]
        reg.register(Knob(
            "k", "g", "client", lo=1, hi=64, step=4,
            get=lambda: val[0], set=lambda v: val.__setitem__(0, v),
        ))
        hc = HillClimber(
            reg, Objective("syn.frames_total"), store=TimeSeriesStore()
        )
        daemon = AutotuneDaemon(hc, interval_s=5.0)
        snap = daemon.snapshot()
        assert snap["mode"] == "on" and snap["knobs_total"] == 1
        assert snap["interval_s"] == 5.0
        for key in ("current", "lo", "hi", "actuations_total",
                    "reverts_total", "kept_total", "min_actuated",
                    "max_actuated", "pinned"):
            assert key in snap["k"], key
        for key in ("ticks_total", "decisions_total",
                    "guardrail_trips_total", "probe_open"):
            assert key in snap, key
        # numeric leaves flatten for the history sampler / Prometheus
        from psana_ray_tpu.obs.registry import flatten_numeric

        leaves = []
        flatten_numeric(("autotune",), snap, leaves)
        keys = {k for k, _ in leaves}
        assert "autotune.k.current" in keys
        assert "autotune.k.actuations_total" in keys

    def test_add_autotune_args_and_configure(self):
        import argparse

        p = argparse.ArgumentParser()
        add_autotune_args(p)
        a = p.parse_args([])
        assert a.autotune == "off"
        assert configure_autotune_from_args(a, [], Objective("x")) is None

        a = p.parse_args(["--autotune", "observe", "--autotune_interval", "9"])
        val = [8.0]
        knob = Knob(
            "k", "g", "client", lo=1, hi=64, step=4,
            get=lambda: val[0], set=lambda v: val.__setitem__(0, v),
        )
        from psana_ray_tpu.obs.timeseries import (
            default_history,
            stop_default_history,
        )

        had_history = default_history() is not None
        daemon = configure_autotune_from_args(
            a, [knob, None], Objective("syn.frames_total"),
            pinned={"other": "reason"},
        )
        try:
            assert daemon is not None
            assert daemon.interval_s == 9.0
            assert daemon.controller.registry.mode == "observe"
            assert daemon.controller.registry.eligible() == ["k"]
            assert daemon.controller.guardrails  # defaults armed
            # the controller needs measured history: configure started
            # the process sampler when none was running
            assert default_history() is not None
        finally:
            daemon.stop()
            from psana_ray_tpu.obs import MetricsRegistry

            MetricsRegistry.default().unregister("autotune")
            if not had_history:
                # restore process-global state: a leaked sampler would
                # flip test_flight's no-history pin (and register a
                # stray "timeseries" source) for the rest of the run
                stop_default_history()
                MetricsRegistry.default().unregister("timeseries")

    def test_default_guardrails_are_inert_on_missing_keys(self):
        store = TimeSeriesStore()
        for g in default_guardrails():
            assert g.tripped(store) is False

    def test_all_cli_parsers_expose_the_flag(self):
        from psana_ray_tpu.producer import parse_arguments

        _, a = parse_arguments(["--autotune", "observe"])
        assert a.autotune == "observe"
        # consumer / sfx / queue_server wire add_autotune_args in main();
        # source-level pin keeps the wiring from silently rotting
        import inspect

        import psana_ray_tpu.consumer as consumer
        import psana_ray_tpu.queue_server as queue_server
        import psana_ray_tpu.sfx as sfx

        for mod in (consumer, sfx, queue_server):
            assert "add_autotune_args" in inspect.getsource(mod.main), mod


# ---------------------------------------------------------------------------
# 7. zero-copy pins with the controller LIVE
# ---------------------------------------------------------------------------

class TestZeroCopyWithControllerLive:
    def test_streaming_relay_pins_hold_while_controller_actuates(self):
        """ISSUE 15 acceptance: copies/frame == 1.00 and steady-state
        pool churn == 0 with a live controller actuating the drain
        chunk/poll and the stream credit window MID-STREAM (instrumented
        private pool, same harness as test_wire_zero_copy)."""
        pool = BufferPool()
        q = RingBuffer(32)
        srv = TcpQueueServer(q, host="127.0.0.1", pool=pool).serve_background()
        prod = TcpQueueClient("127.0.0.1", srv.port, pool=pool)
        cons = TcpQueueClient("127.0.0.1", srv.port, pool=pool)
        n = 48
        control = DrainControl(chunk=8, poll_s=0.002)
        store = TimeSeriesStore()
        reg = KnobRegistry()
        reg.register(drain_chunk_knob(control))
        reg.register(drain_poll_knob(control))
        hc = HillClimber(
            reg, Objective("syn.frames_total", window_s=3.0),
            store=store, hold_ticks=1, settle_ticks=0, cooldown_ticks=0,
        )
        stop = threading.Event()
        fed = [0.0]

        def controller_loop():
            t = 1000.0
            while not stop.is_set():
                fed[0] += 100.0
                t += 1.0
                store.record({"syn": {"frames_total": fed[0]}}, now=t)
                hc.tick()
                # stream-window knob rides the CONSUMER connection once
                # subscribed — resize it live too
                try:
                    cons.set_stream_window(16 + (int(t) % 3) * 16)
                except RuntimeError:
                    pass  # not subscribed yet
                time.sleep(0.005)

        try:

            def produce():
                for i in range(n):
                    assert prod.put_wait(_rec(i), timeout=30)
                assert prod.put_wait(EndOfStream(total_events=n), timeout=30)

            t = threading.Thread(target=produce, daemon=True)
            ctl = threading.Thread(target=controller_loop, daemon=True)
            c0 = WIRE.stats()
            t.start()
            ctl.start()
            seen = 0
            m0 = None
            for batch in batches_from_queue(
                cons, 8, poll_interval_s=0.002, control=control
            ):
                if m0 is None:
                    m0 = pool.stats()  # steady state: after first batch
                seen += batch.num_valid
            t.join(timeout=30)
            stop.set()
            ctl.join(timeout=5)
            assert seen == n
            assert cons._stream is not None  # the drain streamed
            d = WIRE.stats()
            copies = d["copies_total"] - c0["copies_total"]
            assert copies == n, f"expected 1 copy/frame, got {copies}/{n}"
            m1 = pool.stats()
            churn = m1["churn_misses"] - m0["churn_misses"]
            assert churn == 0, f"controller-live path churned {churn} allocs"
            # the controller actually actuated mid-stream
            snap = reg.snapshot()
            acted = sum(
                snap[k]["actuations_total"]
                for k in ("drain_chunk", "drain_poll_s")
            )
            assert acted > 0, "controller never actuated during the drain"
        finally:
            stop.set()
            prod.disconnect()
            cons.disconnect()
            srv.shutdown()
            from psana_ray_tpu.transport.ring import EMPTY as _EMPTY

            while True:
                item = q.get()
                if item is _EMPTY:
                    break
                release = getattr(item, "release", None)
                if release is not None:
                    release()


# ---------------------------------------------------------------------------
# storage / infeed / pool knob round-trips
# ---------------------------------------------------------------------------

class TestOtherKnobTargets:
    def test_fsync_and_ram_items_knobs(self, tmp_path):
        from psana_ray_tpu.storage import DurableRingBuffer, SegmentLog

        log = SegmentLog(str(tmp_path / "q"), segment_bytes=1 << 20)
        q = DurableRingBuffer(log, maxsize=16, ram_items=8)
        try:
            fk = fsync_batch_knob(log)
            assert fk is not None
            fk.set(fk.clamp(128))
            assert log.fsync_batch_n == 128
            rk = ram_items_knob(q)
            assert rk is not None
            rk.set(rk.clamp(24))
            assert q.ram_items == 24
            assert fsync_batch_knob(object()) is None
            assert ram_items_knob(object()) is None
        finally:
            q.close()
            log.close()

    def test_bufpool_retention_knob(self):
        pool = BufferPool()
        k = bufpool_retention_knob(pool)
        assert k is not None
        k.set(9)
        assert pool.min_per_class == 9

    def test_prefetch_depth_resizes_live(self):
        from psana_ray_tpu.infeed.pipeline import DevicePrefetcher

        batches = iter([])
        pf = DevicePrefetcher(batches, prefetch_depth=2, to_device=lambda b: b)
        try:
            k = prefetch_depth_knob(pf)
            assert k is not None
            k.set(5)
            assert pf.prefetch_depth == 5
            assert pf._buf.maxsize == 5
        finally:
            pf.close()

    def test_infeed_pipeline_clips_depth_to_the_arena_bound(self):
        from psana_ray_tpu.infeed.pipeline import InfeedPipeline

        q = RingBuffer(4)
        pipe = InfeedPipeline(
            q, batch_size=2, prefetch_depth=2, place_on_device=False,
            batcher_buffers=8,
        )
        try:
            # 8 arenas => depth may never exceed 8 - 4 = 4
            assert pipe.set_prefetch_depth(99) == 4
            assert pipe.prefetch_depth == 4
            assert pipe.set_prefetch_depth(1) == 1
        finally:
            pipe.close()
            q.close()

    def test_drain_control_dials_are_honored(self):
        """The drain loop re-reads chunk/poll per iteration: with
        chunk=1 every pop returns at most one record."""
        q = RingBuffer(32)
        for i in range(6):
            q.put(_rec(i))
        q.put(EndOfStream(total_events=6))
        control = DrainControl(chunk=1, poll_s=0.001)
        pops = []
        real_get_batch = q.get_batch

        def spying_get_batch(max_items, timeout=None):
            pops.append(max_items)
            return real_get_batch(max_items, timeout=timeout)

        q.get_batch = spying_get_batch
        seen = 0
        for batch in batches_from_queue(q, 4, control=control):
            seen += batch.num_valid
        assert seen == 6
        assert pops and all(p == 1 for p in pops)
