"""Back-compat anchors for the two original static screens (ISSUE 1/2).

The NameError scan and the hot-path allocation-idiom screen that used to
live here as ad-hoc test code are now first-class checkers in
:mod:`psana_ray_tpu.lint` (ISSUE 3) — registry, shared parse, central
allowlist with rot detection, CLI. ``tests/test_lint.py`` is the full
tier-1 driver; these two tests pin the MIGRATED screens by name so the
original invariants keep their own failure identity (a hot-path
regression fails here exactly as it did pre-framework, not just inside
an aggregate lint test).
"""

from __future__ import annotations

from psana_ray_tpu.lint import run_lint


def _findings(checker: str):
    result = run_lint(checkers=[checker])
    return [f for f in result.findings if f.checker == checker]


def test_no_undefined_names():
    """The ISSUE 1 screen: latent NameErrors (deferred annotations,
    version-gated builtins like py3.10 ExceptionGroup) are tier-1."""
    found = _findings("undefined-name")
    assert not found, "\n".join(f.render() for f in found)


def test_hot_path_has_no_per_frame_allocation_idioms():
    """The ISSUE 2 screen: the zero-copy datapath must not regrow
    .tobytes()/.to_bytes(/raw .recv(/bytes(...) per-frame idioms."""
    found = _findings("hot-alloc")
    assert not found, "\n".join(f.render() for f in found)
