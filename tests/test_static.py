"""Static NameError screen over the package (satellite of ISSUE 1).

The seed shipped ``List[float]`` in utils/metrics.py with ``List`` never
imported — invisible to the suite because ``from __future__ import
annotations`` defers evaluation, but a latent NameError for any consumer
that introspects the annotations. This test makes that class of bug a
tier-1 failure: pyflakes when the environment has it, else a conservative
stdlib AST checker that flags loads of names never bound anywhere in the
module (no false positives by construction: any binding anywhere in the
file — any scope — whitelists the name).

Fast (< 1 s for the whole package) and dependency-free, so it is always
``-m 'not slow'``-eligible.
"""

import ast
import builtins
import pathlib

import pytest

PACKAGE_ROOT = pathlib.Path(__file__).resolve().parent.parent
SOURCES = sorted((PACKAGE_ROOT / "psana_ray_tpu").rglob("*.py")) + [
    PACKAGE_ROOT / "bench.py"
]

# Module-level / implicit names that are defined without an AST binding.
_IMPLICIT = {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__debug__", "__annotations__",
    "__class__", "__path__", "__qualname__", "__module__", "__dict__",
}
_ALLOWED = set(dir(builtins)) | _IMPLICIT


class _Binder(ast.NodeVisitor):
    """Collect every name the module binds, in ANY scope (conservative:
    scope-blind union, so cross-scope uses never false-positive)."""

    def __init__(self):
        self.bound = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.bound.add(node.id)
        self.generic_visit(node)

    def _bind_args(self, args: ast.arguments):
        for a in (
            *args.posonlyargs, *args.args, *args.kwonlyargs,
            *filter(None, (args.vararg, args.kwarg)),
        ):
            self.bound.add(a.arg)

    def visit_FunctionDef(self, node):
        self.bound.add(node.name)
        self._bind_args(node.args)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node):
        self.bound.add(node.name)
        self._bind_args(node.args)
        self.generic_visit(node)

    def visit_Lambda(self, node):
        self._bind_args(node.args)
        self.generic_visit(node)

    def visit_ClassDef(self, node):
        self.bound.add(node.name)
        self.generic_visit(node)

    def visit_Import(self, node):
        for alias in node.names:
            self.bound.add(alias.asname or alias.name.split(".")[0])

    def visit_ImportFrom(self, node):
        for alias in node.names:
            if alias.name != "*":
                self.bound.add(alias.asname or alias.name)

    def visit_ExceptHandler(self, node):
        if node.name:
            self.bound.add(node.name)
        self.generic_visit(node)

    def visit_Global(self, node):
        self.bound.update(node.names)

    def visit_Nonlocal(self, node):
        self.bound.update(node.names)

    def visit_MatchAs(self, node):
        if node.name:
            self.bound.add(node.name)
        self.generic_visit(node)

    def visit_MatchStar(self, node):
        if node.name:
            self.bound.add(node.name)
        self.generic_visit(node)

    def visit_MatchMapping(self, node):
        if node.rest:
            self.bound.add(node.rest)
        self.generic_visit(node)


def undefined_names(tree: ast.AST):
    """``[(lineno, name), ...]`` loads of names never bound in the file."""
    binder = _Binder()
    binder.visit(tree)
    known = binder.bound | _ALLOWED
    out = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id not in known
        ):
            out.append((node.lineno, node.id))
    return out


def _pyflakes_messages(path):
    """Real pyflakes when available (richer: unused imports stay advisory,
    undefined names fail); None when the environment lacks it."""
    try:
        from pyflakes import api as pyflakes_api
        from pyflakes import reporter as pyflakes_reporter
    except ImportError:
        return None
    import io

    buf = io.StringIO()
    rep = pyflakes_reporter.Reporter(buf, buf)
    pyflakes_api.checkPath(str(path), reporter=rep)
    return [
        line
        for line in buf.getvalue().splitlines()
        # fail only on NameError-class findings; style findings (unused
        # import, redefinition) stay out of tier-1
        if "undefined name" in line or "local variable" in line and "referenced before" in line
    ]


@pytest.mark.parametrize("path", SOURCES, ids=lambda p: str(p.relative_to(PACKAGE_ROOT)))
def test_no_undefined_names(path):
    src = path.read_text()
    tree = ast.parse(src, filename=str(path))  # syntax is checked for free
    flakes = _pyflakes_messages(path)
    if flakes is not None:
        assert not flakes, "pyflakes: " + "; ".join(flakes)
        return
    missing = undefined_names(tree)
    assert not missing, (
        f"{path.name}: names used but never bound (latent NameError): "
        + ", ".join(f"line {ln}: {name}" for ln, name in missing)
    )
