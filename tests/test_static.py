"""Static NameError screen over the package (satellite of ISSUE 1).

The seed shipped ``List[float]`` in utils/metrics.py with ``List`` never
imported — invisible to the suite because ``from __future__ import
annotations`` defers evaluation, but a latent NameError for any consumer
that introspects the annotations. This test makes that class of bug a
tier-1 failure: pyflakes when the environment has it, else a conservative
stdlib AST checker that flags loads of names never bound anywhere in the
module (no false positives by construction: any binding anywhere in the
file — any scope — whitelists the name).

Fast (< 1 s for the whole package) and dependency-free, so it is always
``-m 'not slow'``-eligible.
"""

import ast
import builtins
import pathlib

import pytest

PACKAGE_ROOT = pathlib.Path(__file__).resolve().parent.parent
SOURCES = sorted((PACKAGE_ROOT / "psana_ray_tpu").rglob("*.py")) + [
    PACKAGE_ROOT / "bench.py"
]

# Module-level / implicit names that are defined without an AST binding.
_IMPLICIT = {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__debug__", "__annotations__",
    "__class__", "__path__", "__qualname__", "__module__", "__dict__",
}
_ALLOWED = set(dir(builtins)) | _IMPLICIT


class _Binder(ast.NodeVisitor):
    """Collect every name the module binds, in ANY scope (conservative:
    scope-blind union, so cross-scope uses never false-positive)."""

    def __init__(self):
        self.bound = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.bound.add(node.id)
        self.generic_visit(node)

    def _bind_args(self, args: ast.arguments):
        for a in (
            *args.posonlyargs, *args.args, *args.kwonlyargs,
            *filter(None, (args.vararg, args.kwarg)),
        ):
            self.bound.add(a.arg)

    def visit_FunctionDef(self, node):
        self.bound.add(node.name)
        self._bind_args(node.args)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node):
        self.bound.add(node.name)
        self._bind_args(node.args)
        self.generic_visit(node)

    def visit_Lambda(self, node):
        self._bind_args(node.args)
        self.generic_visit(node)

    def visit_ClassDef(self, node):
        self.bound.add(node.name)
        self.generic_visit(node)

    def visit_Import(self, node):
        for alias in node.names:
            self.bound.add(alias.asname or alias.name.split(".")[0])

    def visit_ImportFrom(self, node):
        for alias in node.names:
            if alias.name != "*":
                self.bound.add(alias.asname or alias.name)

    def visit_ExceptHandler(self, node):
        if node.name:
            self.bound.add(node.name)
        self.generic_visit(node)

    def visit_Global(self, node):
        self.bound.update(node.names)

    def visit_Nonlocal(self, node):
        self.bound.update(node.names)

    def visit_MatchAs(self, node):
        if node.name:
            self.bound.add(node.name)
        self.generic_visit(node)

    def visit_MatchStar(self, node):
        if node.name:
            self.bound.add(node.name)
        self.generic_visit(node)

    def visit_MatchMapping(self, node):
        if node.rest:
            self.bound.add(node.rest)
        self.generic_visit(node)


def undefined_names(tree: ast.AST):
    """``[(lineno, name), ...]`` loads of names never bound in the file."""
    binder = _Binder()
    binder.visit(tree)
    known = binder.bound | _ALLOWED
    out = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id not in known
        ):
            out.append((node.lineno, node.id))
    return out


def _pyflakes_messages(path):
    """Real pyflakes when available (richer: unused imports stay advisory,
    undefined names fail); None when the environment lacks it."""
    try:
        from pyflakes import api as pyflakes_api
        from pyflakes import reporter as pyflakes_reporter
    except ImportError:
        return None
    import io

    buf = io.StringIO()
    rep = pyflakes_reporter.Reporter(buf, buf)
    pyflakes_api.checkPath(str(path), reporter=rep)
    return [
        line
        for line in buf.getvalue().splitlines()
        # fail only on NameError-class findings; style findings (unused
        # import, redefinition) stay out of tier-1
        if "undefined name" in line or "local variable" in line and "referenced before" in line
    ]


@pytest.mark.parametrize("path", SOURCES, ids=lambda p: str(p.relative_to(PACKAGE_ROOT)))
def test_no_undefined_names(path):
    src = path.read_text()
    tree = ast.parse(src, filename=str(path))  # syntax is checked for free
    flakes = _pyflakes_messages(path)
    if flakes is not None:
        assert not flakes, "pyflakes: " + "; ".join(flakes)
        return
    missing = undefined_names(tree)
    assert not missing, (
        f"{path.name}: names used but never bound (latent NameError): "
        + ", ".join(f"line {ln}: {name}" for ln, name in missing)
    )


# ---------------------------------------------------------------------------
# Zero-copy invariant screen (ISSUE 2 satellite): the transport/infeed hot
# path must not regrow per-frame allocation idioms. Every frame payload
# travels as (a) a wire_parts() memoryview out via sendmsg, (b) a pooled
# recv_into lease in, (c) ONE np.copyto into the batch arena — so
# `.tobytes()` (frame-sized serialization copy), `.to_bytes(` calls
# (contiguous assembly), raw `.recv(` (fresh bytes per chunk) and
# frame-scale `bytes(...)` materialization are BANNED in these files,
# except for the reviewed, size-bounded uses below.

import re  # noqa: E402

HOT_PATH_FILES = [
    "psana_ray_tpu/records.py",
    "psana_ray_tpu/transport/codec.py",
    "psana_ray_tpu/transport/tcp.py",
    "psana_ray_tpu/transport/shm_ring.py",
    "psana_ray_tpu/infeed/batcher.py",
]

_BANNED = [
    # frame-sized ndarray -> bytes serialization copy
    ("tobytes", re.compile(r"\.tobytes\(")),
    # record -> contiguous bytes assembly (wire_parts exists instead)
    ("to_bytes-call", re.compile(r"\.to_bytes\(")),
    # chunked recv(): a fresh bytes object per chunk; use _recv_into on
    # a pooled buffer (recv_into is fine and not matched)
    ("raw-recv", re.compile(r"\.recv\(")),
    # bytes(...) materialization of a buffer (lookbehind skips nbytes(,
    # from_bytes(, slot_bytes( etc.)
    ("bytes-materialize", re.compile(r"(?<![A-Za-z0-9_.])bytes\(")),
]

# (file suffix, line substring) — each entry is a REVIEWED exception:
# control-plane reads of a few bytes, 1-byte tag peeks, or the legacy
# contiguous encoders that back-compat callers still use off the hot
# path. An entry that stops matching fails the test too (allowlist rot).
_HOT_ALLOWLIST = [
    ("transport/tcp.py", "return bytes(buf)"),  # _recv_exact: <=8-byte control fields
    ("transport/codec.py", "return [TAG_RECORD + item.to_bytes()]"),  # EOS: header-only
    ("transport/codec.py", "return TAG_RECORD + item.to_bytes()"),  # legacy encode_payload
    ("transport/codec.py", "tag = bytes(buf[:1])"),  # 1-byte tag peek
    ("transport/shm_ring.py", "if bytes(mv[:1]) == _TAG_VOID:"),  # 1-byte tag peek
    ("records.py", "return header + payload.tobytes()"),  # legacy FrameRecord.to_bytes
    ("records.py", "data = item.to_bytes()  # header-only, tiny"),  # encode_into EOS
]


def _allowed(rel: str, line: str) -> bool:
    return any(rel.endswith(suf) and sub in line for suf, sub in _HOT_ALLOWLIST)


def test_hot_path_has_no_per_frame_allocation_idioms():
    violations, matched_allow = [], set()
    for rel in HOT_PATH_FILES:
        path = PACKAGE_ROOT / rel
        for ln, line in enumerate(path.read_text().splitlines(), 1):
            code = line.split("#", 1)[0] if not line.lstrip().startswith("#") else ""
            if not code.strip():
                continue
            for tag, pat in _BANNED:
                if not pat.search(code):
                    continue
                if _allowed(rel, line):
                    matched_allow.add((rel, line.strip()))
                    continue
                violations.append(f"{rel}:{ln} [{tag}] {line.strip()}")
    assert not violations, (
        "per-frame allocation idiom on the zero-copy hot path (use "
        "wire_parts()/sendmsg, pooled recv_into, push_view — or add a "
        "reviewed allowlist entry):\n  " + "\n  ".join(violations)
    )
    stale = [
        (suf, sub)
        for suf, sub in _HOT_ALLOWLIST
        if not any(rel.endswith(suf) and sub in line for rel, line in matched_allow)
    ]
    assert not stale, f"allowlist entries no longer match anything (remove them): {stale}"
