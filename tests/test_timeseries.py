"""ISSUE 13 telemetry plane: history rings, federation, exemplars, console.

Five layers, fast and jax-free:

1. :class:`SeriesRing` / :class:`TimeSeriesStore` — bounded ring
   semantics, zero-alloc steady-state appends, and the read-time
   delta/rate/EWMA/percentile views against hand-computed values;
2. federation — a :class:`ClusterCollector` over LIVE queue servers via
   the 'N' metrics RPC and a live HTTP ``/federate`` endpoint, with the
   dead-peer and old-peer (degrade loudly) paths pinned;
3. SLO burn-rate alerts — edge-triggered breadcrumbs + the active gauge
   over a deterministic synthetic peer;
4. exemplars — a latency histogram's retained trace id resolves through
   ``trace_merge --exemplar`` to the frame's merged timeline, including
   the gateway-completed path end to end;
5. ``obs.top --once`` — golden-ish render over a LIVE 3-process
   queue-server mini-cluster (subprocess CLIs, the acceptance row).
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from psana_ray_tpu.obs import trace_merge
from psana_ray_tpu.obs.collector import (
    ALERT_SLO_BURN,
    ClusterCollector,
    PEER_DEGRADED,
    PEER_DOWN,
    PEER_UP,
    parse_peer,
)
from psana_ray_tpu.obs.console import main as top_main, render, sparkline
from psana_ray_tpu.obs.flight import FLIGHT
from psana_ray_tpu.obs.registry import MetricsRegistry, federation_payload
from psana_ray_tpu.obs.timeseries import (
    HistorySampler,
    SeriesRing,
    TimeSeriesStore,
)
from psana_ray_tpu.obs.tracing import Tracer
from psana_ray_tpu.transport.ring import RingBuffer
from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer
from psana_ray_tpu.utils.metrics import LatencyStats


# ---------------------------------------------------------------------------
# 1. ring + store semantics
# ---------------------------------------------------------------------------

class TestSeriesRing:
    def test_bounded_and_ordered(self):
        r = SeriesRing(capacity=8)
        for i in range(30):
            r.append(float(i), float(i * 10))
        assert len(r) == 8
        pts = r.samples()
        assert [t for t, _ in pts] == [float(i) for i in range(22, 30)]
        assert pts[-1] == (29.0, 290.0)
        assert r.last() == (29.0, 290.0)
        # partial tail
        assert [v for _, v in r.samples(3)] == [270.0, 280.0, 290.0]

    def test_capacity_validation(self):
        with pytest.raises(ValueError):
            SeriesRing(capacity=1)

    def test_append_is_allocation_free_steady_state(self):
        """The zero-alloc-on-sample contract: appends into a warmed ring
        allocate nothing (index arithmetic into preallocated arrays)."""
        r = SeriesRing(capacity=64)
        for i in range(128):  # warm: wrap at least once
            r.append(float(i), 1.0)
        before = sys.getallocatedblocks()
        for i in range(10_000):
            r.append(float(i), 2.0)
        grew = sys.getallocatedblocks() - before
        assert grew <= 16, f"ring append allocated ({grew} blocks / 10k appends)"


class TestTimeSeriesStore:
    def _filled(self):
        s = TimeSeriesStore(capacity=16)
        # a counter climbing 5/s and a sawtooth gauge, 1 Hz for 10 s
        for i in range(10):
            s.record(
                {"src": {"frames_total": i * 5, "depth": float(i % 4)}},
                now=100.0 + i,
            )
        return s

    def test_flatten_and_keys(self):
        s = self._filled()
        assert s.keys() == ["src.depth", "src.frames_total"]
        assert s.last("src.frames_total") == 45.0
        assert s.last("missing") is None

    def test_delta_rate_windows(self):
        s = self._filled()
        assert s.delta("src.frames_total") == 45.0
        assert s.rate("src.frames_total") == pytest.approx(5.0)
        # window: only the last ~4 s participate
        assert s.rate("src.frames_total", window_s=4.0) == pytest.approx(5.0)
        assert s.delta("src.frames_total", window_s=2.0) == pytest.approx(10.0)
        assert s.rate("missing") is None

    def test_percentile_and_ewma(self):
        s = self._filled()
        # depth cycles 0,1,2,3 — median 1 or 2, p0 = 0, p99 = 3
        assert s.percentile("src.depth", 0.0) == 0.0
        assert s.percentile("src.depth", 0.99) == 3.0
        ewma = s.ewma("src.depth", alpha=1.0)  # alpha 1 = last value
        assert ewma == s.last("src.depth")

    def test_tail_bounded_and_json_safe(self):
        s = self._filled()
        tail = s.tail(3)
        assert set(tail) == {"src.depth", "src.frames_total"}
        assert len(tail["src.frames_total"]) == 3
        json.dumps(tail)  # flight dumps embed this verbatim

    def test_ring_eviction_through_store(self):
        s = TimeSeriesStore(capacity=4)
        for i in range(10):
            s.record({"a": {"v": i}}, now=float(i))
        assert [v for _, v in s.series("a.v")] == [6.0, 7.0, 8.0, 9.0]

    def test_sampler_sweeps_registry(self):
        reg = MetricsRegistry()
        n = {"count_total": 0}
        reg.register("fake", lambda: dict(n))
        sampler = HistorySampler(registry=reg, interval_s=1.0, capacity=8)
        sampler.sample_once(now=1.0)
        n["count_total"] = 7
        sampler.sample_once(now=2.0)
        assert sampler.store.delta("fake.count_total") == 7.0
        snap = sampler.snapshot()
        assert snap["sweeps_total"] == 2
        assert snap["keys"] == 1


# ---------------------------------------------------------------------------
# 2. federation over live control surfaces
# ---------------------------------------------------------------------------

def test_parse_peer_specs():
    assert parse_peer("tcp://h:9") == ("tcp", "h:9")
    assert parse_peer("h:9") == ("tcp", "h:9")
    assert parse_peer("http://h:9/") == ("http", "http://h:9")
    with pytest.raises(ValueError):
        parse_peer("not-a-peer")


class TestFederation:
    def test_tcp_metrics_rpc_merges_host_tagged(self):
        srv = TcpQueueServer(RingBuffer(10), host="127.0.0.1").serve_background()
        srv2 = TcpQueueServer(RingBuffer(10), host="127.0.0.1").serve_background()
        c = ClusterCollector(
            [f"127.0.0.1:{srv.port}", f"127.0.0.1:{srv2.port}"],
            register=False,
        )
        try:
            states = c.poll_once()
            assert set(states.values()) == {PEER_UP}
            peers = c.peers()
            assert len(peers) == 2
            for p in peers:
                assert p.host and p.pid  # host-tagged
            # two sweeps -> every peer store holds series
            c.poll_once()
            for label, store in c.stores().items():
                assert store.snapshot()["samples_total"] == 2, label
        finally:
            c.stop()
            srv.shutdown()
            srv2.shutdown()

    def test_dead_peer_degrades_loudly_and_survivors_merge(self):
        srv = TcpQueueServer(RingBuffer(10), host="127.0.0.1").serve_background()
        srv2 = TcpQueueServer(RingBuffer(10), host="127.0.0.1").serve_background()
        c = ClusterCollector(
            [f"127.0.0.1:{srv.port}", f"127.0.0.1:{srv2.port}"],
            register=False, pull_timeout_s=2.0,
        )
        try:
            assert set(c.poll_once().values()) == {PEER_UP}
            before = FLIGHT.count_of("collector_peer_down")
            srv2.shutdown()
            states = c.poll_once()
            assert states[f"127.0.0.1:{srv.port}"] == PEER_UP
            assert states[f"127.0.0.1:{srv2.port}"] == PEER_DOWN
            # loud: a breadcrumb per transition, survivor unaffected
            assert FLIGHT.count_of("collector_peer_down") == before + 1
            snap = c.snapshot()
            assert snap["peers_up"] == 1 and snap["peers_down"] == 1
            # the dead peer's already-merged history is retained
            dead = c.store(f"127.0.0.1:{srv2.port}")
            assert dead.snapshot()["samples_total"] == 1
        finally:
            c.stop()
            srv.shutdown()

    def test_old_tcp_peer_marks_degraded(self, monkeypatch):
        """A pre-ISSUE-13 server answers the metrics op with an error
        dict (its GroupRegistry rejects the unknown op) — the peer must
        surface as DEGRADED, loudly, never as silently absent."""
        import psana_ray_tpu.transport.evloop as evloop_mod

        srv = TcpQueueServer(RingBuffer(10), host="127.0.0.1").serve_background()
        monkeypatch.setattr(
            evloop_mod, "_metrics_rpc_payload",
            lambda: (_ for _ in ()).throw(RuntimeError("old peer")),
        )
        c = ClusterCollector([f"127.0.0.1:{srv.port}"], register=False)
        try:
            before = FLIGHT.count_of("collector_peer_degraded")
            states = c.poll_once()
            assert list(states.values()) == [PEER_DEGRADED]
            assert FLIGHT.count_of("collector_peer_degraded") == before + 1
        finally:
            c.stop()
            srv.shutdown()

    def test_http_peer_federate_and_healthz_fallback(self):
        from psana_ray_tpu.obs.exporter import MetricsServer

        reg = MetricsRegistry()
        reg.register("fake", lambda: {"count_total": 3})
        ms = MetricsServer(registry=reg, host="127.0.0.1", port=0).start()
        # an OLD http peer: /healthz only (pre-/federate exporter)
        import http.server

        class _OldHandler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path == "/healthz":
                    body = json.dumps({"legacy": {"depth": 4}}).encode()
                    self.send_response(200)
                else:
                    self.send_response(404)
                    body = b"{}"
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        old = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _OldHandler)
        t = threading.Thread(target=old.serve_forever, daemon=True)
        t.start()
        c = ClusterCollector(
            [
                f"http://127.0.0.1:{ms.port}",
                f"http://127.0.0.1:{old.server_address[1]}",
            ],
            register=False,
        )
        try:
            states = c.poll_once()
            assert states[f"http://127.0.0.1:{ms.port}"] == PEER_UP
            assert (
                states[f"http://127.0.0.1:{old.server_address[1]}"]
                == PEER_DEGRADED
            )
            up = c.store(f"http://127.0.0.1:{ms.port}")
            assert up.last("fake.count_total") == 3.0
            # the degraded peer's snapshot still merged
            deg = c.store(f"http://127.0.0.1:{old.server_address[1]}")
            assert deg.last("legacy.depth") == 4.0
        finally:
            c.stop()
            ms.close()
            old.shutdown()
            old.server_close()

    def test_federation_payload_shape(self):
        p = federation_payload(MetricsRegistry())
        assert p["ok"] and p["host"] and p["pid"] == os.getpid()
        json.dumps(p)


# ---------------------------------------------------------------------------
# 3. SLO burn-rate alerts (deterministic synthetic peer)
# ---------------------------------------------------------------------------

class TestAlerts:
    def _collector_with_synthetic_peer(self, monkeypatch, payloads):
        import psana_ray_tpu.obs.collector as collector_mod

        feed = iter(payloads)
        monkeypatch.setattr(
            collector_mod._Peer, "pull", lambda self, t: next(feed)
        )
        return ClusterCollector(
            ["127.0.0.1:1"], register=False,
            slo_target=0.99, burn_threshold=2.0, burn_window_s=60.0,
        )

    @staticmethod
    def _payload(goodput, completed):
        return {
            "ok": True, "host": "h", "pid": 1,
            "metrics": {
                "gateway": {
                    "goodput_total": goodput, "completed_total": completed,
                }
            },
        }

    def test_burn_alert_fires_once_and_clears(self, monkeypatch):
        # window attainment 0.5 => burn (1-0.5)/(1-0.99) = 50 >> 2
        c = self._collector_with_synthetic_peer(
            monkeypatch,
            [
                self._payload(0, 0),
                self._payload(50, 100),    # burning
                self._payload(55, 110),    # still burning
                self._payload(1055, 1110),  # recovery begins
                self._payload(2055, 2110),  # in-window attainment back to 1.0
            ],
        )
        before = FLIGHT.count_of("slo_alert")
        c.poll_once(now=1000.0)
        assert c.active_alerts() == []
        c.poll_once(now=1010.0)
        active = c.active_alerts()
        assert [a["alert"] for a in active] == [ALERT_SLO_BURN]
        assert FLIGHT.count_of("slo_alert") == before + 1
        # still firing: edge-triggered, no second breadcrumb
        c.poll_once(now=1020.0)
        assert FLIGHT.count_of("slo_alert") == before + 1
        assert c.snapshot()["alerts_active"] == 1
        # recovery: once the burn WINDOW holds only clean completions
        # (goodput == completed over the trailing 60 s), the gauge drops
        # and the cleared crumb lands
        cleared_before = FLIGHT.count_of("slo_alert_cleared")
        c.poll_once(now=1070.0)
        c.poll_once(now=1080.0)
        assert c.active_alerts() == []
        assert FLIGHT.count_of("slo_alert_cleared") == cleared_before + 1
        assert c.snapshot()["alerts_fired_total"] >= 1

    def test_stall_and_replication_alerts(self, monkeypatch):
        payload = {
            "ok": True, "host": "h", "pid": 1,
            "metrics": {
                "stalls": {"degraded": 1},
                "replication": {"lag_records": 5000},
            },
        }
        c = self._collector_with_synthetic_peer(monkeypatch, [payload])
        c.poll_once(now=2000.0)
        kinds = {a["alert"] for a in c.active_alerts()}
        assert kinds == {"stall", "replication_lag"}


# ---------------------------------------------------------------------------
# 4. exemplars: histogram bucket -> trace_merge --exemplar -> timeline
# ---------------------------------------------------------------------------

class TestExemplars:
    def test_latency_stats_retains_exemplar_per_bucket(self):
        ls = LatencyStats()
        ls.observe(0.004, exemplar=0xABC)   # le_5 bucket
        ls.observe(0.180, exemplar=0xDEF)   # le_250 bucket
        ls.observe(0.190)                   # no exemplar: keeps 0xDEF
        ex = ls.exemplars()
        assert ex["le_5"]["trace_id"] == "0xabc"
        assert ex["le_250"]["trace_id"] == "0xdef"
        snap = ls.snapshot()
        assert snap["exemplars"]["le_250"]["ms"] == pytest.approx(180.0)
        json.dumps(snap)

    def test_exemplars_excluded_from_numeric_flatten(self):
        """Exemplars are LINKS for the drill-down, not series: the
        shared flatten grammar must skip the subtree whole — no bogus
        per-bucket gauge on /metrics, no history ring per bucket."""
        from psana_ray_tpu.obs.registry import flatten_numeric

        ls = LatencyStats()
        ls.observe(0.180, exemplar=0xDEF)
        leaves = []
        flatten_numeric(("lat",), ls.snapshot(), leaves)
        keys = [k for k, _ in leaves]
        assert not any("exemplar" in k for k in keys), keys
        assert "lat.count" in keys  # the real series still flatten
        # ...and therefore the history store never mints exemplar rings
        store = TimeSeriesStore(capacity=8)
        store.record({"lat": ls.snapshot()})
        assert not any("exemplar" in k for k in store.keys())

    def test_exemplar_resolves_through_trace_merge(self, tmp_path, capsys):
        tid = 0x51AB
        tr = Tracer().configure(str(tmp_path), sample_every=1, process="consumer")
        t0 = time.monotonic()
        tr.span(tid, "queue_dwell", t0, t0 + 0.010)
        tr.span(tid, "dispatch", t0 + 0.010, t0 + 0.015)
        tr.span(0x9999, "dispatch", t0, t0 + 0.001)  # another frame: filtered
        tr.close()
        out = str(tmp_path / "merged.json")
        rc = trace_merge.main(
            ["--exemplar", hex(tid), str(tmp_path), "--out", out]
        )
        assert rc == 0
        printed = capsys.readouterr().out
        assert "queue_dwell" in printed and "dispatch" in printed
        doc = json.load(open(out))
        frame_spans = [
            e for e in doc["traceEvents"]
            if e.get("ph") == "X" and e.get("cat") == "frame"
        ]
        assert len(frame_spans) == 2  # 0x9999 filtered out
        assert all(
            e["args"]["trace_id"] == hex(tid) for e in frame_spans
        )

    def test_exemplar_resolves_across_process_spools(self, tmp_path, capsys):
        """The acceptance wording: a bucket's exemplar resolves to a
        LINKED cross-host timeline — spans for one trace id from
        multiple process spools merge onto one ordered timeline."""
        tid = 0x7777
        t0 = time.monotonic()
        for proc, (name, a, b) in {
            "producer": ("enqueue", 0.000, 0.001),
            "queue_server": ("queue_dwell", 0.001, 0.012),
            "consumer": ("dispatch", 0.012, 0.016),
        }.items():
            tr = Tracer().configure(str(tmp_path), sample_every=1, process=proc)
            tr.span(tid, name, t0 + a, t0 + b)
            tr.close()
        rc = trace_merge.main(
            ["--exemplar", hex(tid), str(tmp_path),
             "--out", str(tmp_path / "m.json")]
        )
        assert rc == 0
        printed = capsys.readouterr().out
        assert "3 process(es)" in printed
        # ordered: enqueue before queue_dwell before dispatch
        lines = [ln for ln in printed.splitlines() if "ms" in ln]
        order = [
            next(n for n in ("enqueue", "queue_dwell", "dispatch") if n in ln)
            for ln in lines if any(
                n in ln for n in ("enqueue", "queue_dwell", "dispatch")
            )
        ]
        assert order == ["enqueue", "queue_dwell", "dispatch"]

    def test_exemplar_not_found_exits_nonzero(self, tmp_path):
        tr = Tracer().configure(str(tmp_path), sample_every=1, process="p")
        tr.span(0x1, "dispatch", 0.0, 1.0)
        tr.close()
        rc = trace_merge.main(
            ["--exemplar", "0xFFFF", str(tmp_path),
             "--out", str(tmp_path / "o.json")]
        )
        assert rc == 1

    def test_gateway_completion_stamps_exemplar(self):
        """End to end inside one process: a sampled record through the
        gateway tags the tenant latency histogram's bucket with its
        trace id (the id trace_merge --exemplar then resolves)."""
        from psana_ray_tpu.obs.tracing import TraceContext
        from psana_ray_tpu.records import FrameRecord
        from psana_ray_tpu.serving.gateway import ServingGateway
        from psana_ray_tpu.serving.policy import SloPolicy
        from psana_ray_tpu.serving.telemetry import GatewayTelemetry

        clock = [0.0]
        gw = ServingGateway(
            dispatch=lambda recs, b: None,
            policy=SloPolicy(slo_ms=100.0),
            telemetry=GatewayTelemetry(register=False),
            clock=lambda: clock[0],
        )
        tid = 0xBEEF
        rec = FrameRecord(
            0, 0, np.zeros((1, 4, 4), np.uint16), 9.5,
            trace=TraceContext(trace_id=tid, sampled=True),
        )
        assert gw.offer(rec, tenant="t0")
        clock[0] += 0.004
        assert gw.dispatch_once() == 1
        stats = gw.telemetry.stats()
        ex = stats["t0"]["exemplars"]
        assert any(v["trace_id"] == hex(tid) for v in ex.values())


# ---------------------------------------------------------------------------
# 5. obs.top --once over a live 3-process mini-cluster
# ---------------------------------------------------------------------------

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _start_server(tmp_path, name):
    port_file = str(tmp_path / f"port_{name}")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "psana_ray_tpu.queue_server",
            "--port", "0", "--port_file", port_file,
            "--stall_poll_s", "0", "--queue_size", "64",
            "--history_interval", "0.2",
        ],
        cwd=REPO_ROOT,
        stderr=subprocess.DEVNULL,
    )
    deadline = time.monotonic() + 30
    while not os.path.exists(port_file):
        assert proc.poll() is None, "queue server died on startup"
        assert time.monotonic() < deadline, "server never wrote port file"
        time.sleep(0.05)
    return proc, int(open(port_file).read())


class TestObsTopAcceptance:
    def test_once_renders_federated_rows_over_three_processes(
        self, tmp_path, capsys
    ):
        """The ISSUE 13 acceptance row: a live 3-process mini-cluster,
        `obs.top --once` shows host-tagged federated series for all
        three (state up, host:pid column, and the depth the frames we
        pushed actually created)."""
        procs = []
        try:
            servers = [_start_server(tmp_path, f"s{i}") for i in range(3)]
            procs = [p for p, _ in servers]
            ports = [port for _, port in servers]
            # move real counters on server 0: 5 puts, 2 gets -> depth 3
            cli = TcpQueueClient("127.0.0.1", ports[0], reconnect_tries=1)
            from psana_ray_tpu.records import FrameRecord

            for i in range(5):
                assert cli.put_wait(
                    FrameRecord(0, i, np.zeros((1, 8, 8), np.uint16), 9.5),
                    timeout=10.0,
                )
            assert cli.get(deadline=time.monotonic() + 10) is not None
            assert cli.get(deadline=time.monotonic() + 10) is not None
            cli.disconnect()
            peers = ",".join(f"127.0.0.1:{p}" for p in ports)
            rc = top_main(["--peers", peers, "--once", "--settle", "0.5"])
            assert rc == 0
            out = capsys.readouterr().out
            # all three processes present, host-tagged, state up
            for port in ports:
                assert f"127.0.0.1:{port}" in out
            assert out.count(" up ") >= 3 or out.count("up") >= 3
            # the server rows carry REAL host:pid tags from the payload
            for proc in procs:
                assert f":{proc.pid}" in out
            # the pushed frames' depth is visible on server 0's row
            row0 = next(
                ln for ln in out.splitlines() if f"127.0.0.1:{ports[0]}" in ln
            )
            assert " 3 " in row0  # depth column: 5 put - 2 got
            assert "sweeps=2" in out
            # the ISSUE 16 CPU column rides the same federated payload
            # (servers run the 97 Hz profiler by default)
            assert "CPU%" in out
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()

    def test_render_handles_empty_collector(self):
        c = ClusterCollector(["127.0.0.1:1"], register=False)
        try:
            out = render(c)
            assert "psana-ray obs.top" in out
        finally:
            c.stop()

    def test_sparkline_shapes(self):
        assert sparkline([]) == ""
        assert sparkline([1.0, 1.0, 1.0]) == "▁▁▁"
        s = sparkline(list(range(16)), width=8)
        assert len(s) == 8 and s[-1] == "█"
