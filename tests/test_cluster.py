"""Sharded queue cluster (ISSUE 7): partition placement stability,
routing-client semantics, consumer groups with generation-fenced
rebalance, cross-server EOS aggregation, and server-death failover.

Everything here is jax-free and loopback-only. Wall-clock throughput
lives in bench.py's ``cluster-scaling`` section; the tier-1 acceptance
pin below uses the deterministic message-count proxy (the PR 5/6
flake-avoidance convention): with a balanced map over 4 servers no
server hosts more than 3/8 of the stream, so aggregate capacity is
>= 2x any single server's at equal service rates — and every frame is
still delivered exactly through the merged streams.
"""

import threading
import time

import numpy as np
import pytest

from psana_ray_tpu.cluster.client import ClusterClient, parse_cluster_address
from psana_ray_tpu.cluster.coordinator import GroupRegistry
from psana_ray_tpu.cluster.hashring import (
    PartitionMap,
    assign_group_partitions,
    partition_queue_name,
)
from psana_ray_tpu.cluster.telemetry import CLUSTER
from psana_ray_tpu.records import EndOfStream, FrameRecord, is_eos
from psana_ray_tpu.transport import TransportClosed
from psana_ray_tpu.transport.ring import RingBuffer
from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer


def _frame(i, rank=0):
    return FrameRecord(rank, i, np.full((1, 4, 4), float(i), np.float32), 1.0)


def _servers(n, maxsize=64):
    servers = [
        TcpQueueServer(RingBuffer(maxsize), host="127.0.0.1", maxsize=maxsize)
        .serve_background()
        for _ in range(n)
    ]
    addrs = [f"127.0.0.1:{s.port}" for s in servers]
    return servers, addrs


def _shutdown(servers):
    for s in servers:
        try:
            s.shutdown()
        except Exception:
            pass


def _drain_until_eos(cons, budget_s=30.0, batch=16):
    """Drain merged streams until the ONE synthesized EOS; returns
    (event indices in arrival order, eos count)."""
    got, eos = [], 0
    deadline = time.monotonic() + budget_s
    while not eos and time.monotonic() < deadline:
        for item in cons.get_batch_stream(batch, timeout=0.5):
            if is_eos(item):
                eos += 1
            else:
                got.append(item.event_idx)
    return got, eos


# ---------------------------------------------------------------------------
# partition map: rendezvous stability
# ---------------------------------------------------------------------------

class TestPartitionMap:
    ADDRS = [f"10.0.0.{i}:7000" for i in range(1, 9)]  # fixed: deterministic

    def test_deterministic_and_exhaustive(self):
        a = PartitionMap.compute(self.ADDRS[:4], "q", 32)
        b = PartitionMap.compute(self.ADDRS[:4], "q", 32)
        assert a.assignments == b.assignments
        assert sorted(a.assignments) == list(range(32))
        assert set(a.assignments.values()) <= set(self.ADDRS[:4])

    def test_join_moves_at_most_its_expected_share(self):
        """Adding a server moves ONLY partitions the newcomer wins:
        ~1/(N+1) of them in expectation, and never a partition between
        two incumbent servers."""
        P = 64
        before = PartitionMap.compute(self.ADDRS[:4], "q", P)
        after = before.recompute(self.ADDRS[:5])
        moved = after.moved_from(before)
        # every move is TO the newcomer (rendezvous property, exact)
        assert all(after.assignments[p] == self.ADDRS[4] for p in moved)
        # and the share is ~P/5 — allow 2.5x slack over expectation
        assert len(moved) <= int(2.5 * P / 5), len(moved)
        assert after.version == before.version + 1

    def test_death_moves_only_the_dead_servers_partitions(self):
        P = 64
        before = PartitionMap.compute(self.ADDRS[:4], "q", P)
        dead = self.ADDRS[1]
        after = before.recompute([a for a in self.ADDRS[:4] if a != dead])
        moved = set(after.moved_from(before))
        assert moved == set(before.partitions_on(dead))
        # survivors' other partitions did not reshuffle
        for p in range(P):
            if p not in moved:
                assert after.assignments[p] == before.assignments[p]

    def test_group_assignment_disjoint_and_exhaustive(self):
        members = ["m-c", "m-a", "m-b"]
        P = 8
        all_parts = []
        for m in members:
            parts = assign_group_partitions(members, m, P)
            all_parts.extend(parts)
            # every member computes every OTHER member's view identically
            for other in members:
                assert assign_group_partitions(
                    list(reversed(members)), other, P
                ) == assign_group_partitions(members, other, P)
        assert sorted(all_parts) == list(range(P))
        assert assign_group_partitions(members, "not-a-member", P) == ()

    def test_parse_cluster_address(self):
        assert parse_cluster_address("cluster://a:1,b:2") == ["a:1", "b:2"]
        assert parse_cluster_address("a:1, b:2 ,") == ["a:1", "b:2"]
        with pytest.raises(ValueError):
            parse_cluster_address("cluster://")
        with pytest.raises(ValueError):
            parse_cluster_address("cluster://nohostport")


# ---------------------------------------------------------------------------
# routing client: transparent partitioned puts/gets + EOS aggregation
# ---------------------------------------------------------------------------

class TestClusterClient:
    def test_put_get_round_trip_spreads_over_servers(self):
        servers, addrs = _servers(2)
        prod = cons = None
        try:
            # search a queue name whose map puts >=1 partition on EVERY
            # server (ephemeral ports make the hash per-run; the search
            # is deterministic given them)
            qname = _balanced_queue_name(addrs, P=4, per_server_cap=3)
            prod = ClusterClient(addrs, queue_name=qname, n_partitions=4,
                                 maxsize=64)
            cons = ClusterClient(addrs, queue_name=qname, n_partitions=4,
                                 maxsize=64)
            N = 24
            for i in range(N):
                assert prod.put(_frame(i))
            # the partitions are ordinary named queues on their owners
            depths = [s.depth() for s in servers]
            assert sum(depths) == N
            assert all(d > 0 for d in depths), (
                f"one server hosts everything: {depths} — routing is not "
                f"spreading partitions"
            )
            assert prod.put_wait(EndOfStream(0, -1, 1, 1), timeout=10)
            got, eos = _drain_until_eos(cons)
            assert sorted(got) == list(range(N))
            assert eos == 1
            # after the synthesized EOS the drain stays terminated
            assert cons.get_batch_stream(4, timeout=0.2) == []
        finally:
            if prod:
                prod.disconnect()
            if cons:
                cons.disconnect()
            _shutdown(servers)

    def test_eos_waits_for_every_partition_and_every_producer(self):
        """Cross-server EOS: two producer runtimes (ranks 0 and 1 of 2)
        each broadcast their marker; no partition may complete — and no
        synthesized EOS may surface — until BOTH producers' markers
        covered every partition."""
        servers, addrs = _servers(2)
        p0 = p1 = cons = None
        try:
            P = 4
            p0 = ClusterClient(addrs, n_partitions=P, maxsize=64)
            p1 = ClusterClient(addrs, n_partitions=P, maxsize=64)
            cons = ClusterClient(addrs, n_partitions=P, maxsize=64)
            for i in range(8):
                assert p0.put(_frame(i, rank=0))
            assert p0.put_wait(
                EndOfStream(producer_rank=0, shards_done=1, total_shards=2),
                timeout=10,
            )
            # producer 0 finished but producer 1 has not: the stream is
            # NOT over — the consumer must keep waiting, not stop early
            got, eos = [], 0
            deadline = time.monotonic() + 3.0
            while time.monotonic() < deadline and len(got) < 8:
                for item in cons.get_batch_stream(8, timeout=0.2):
                    if is_eos(item):
                        eos += 1
                    else:
                        got.append(item.event_idx)
            assert eos == 0, "EOS surfaced before all producers finished"
            assert sorted(got) == list(range(8))
            for i in range(8, 12):
                assert p1.put(_frame(i, rank=1))
            assert p1.put_wait(
                EndOfStream(producer_rank=1, shards_done=1, total_shards=2),
                timeout=10,
            )
            got2, eos = _drain_until_eos(cons)
            assert sorted(got2) == list(range(8, 12))
            assert eos == 1
        finally:
            for c in (p0, p1, cons):
                if c:
                    c.disconnect()
            _shutdown(servers)

    def test_data_reader_integration_terminates_exactly_once(self):
        """DataReader against a cluster:// address — the existing
        consumer surface works with only an address change."""
        from psana_ray_tpu.config import TransportConfig
        from psana_ray_tpu.consumer import DataReader

        servers, addrs = _servers(2)
        prod = None
        try:
            cfg = TransportConfig(
                address="cluster://" + ",".join(addrs), cluster_partitions=4
            )
            prod = ClusterClient(addrs, n_partitions=4, maxsize=64)
            N = 10
            for i in range(N):
                assert prod.put(_frame(i))
            assert prod.put_wait(EndOfStream(0, -1, 1, 1), timeout=10)
            with DataReader(address=cfg.address, config=cfg) as reader:
                seen = [rec.event_idx for rec in reader.iter_records()]
            assert sorted(seen) == list(range(N))
        finally:
            if prod:
                prod.disconnect()
            _shutdown(servers)

    def test_batches_from_queue_over_cluster(self):
        """The infeed drain (batcher fan-in) over the merged streams:
        fixed-shape batches out, EOS flush, nothing lost."""
        from psana_ray_tpu.infeed.batcher import batches_from_queue

        servers, addrs = _servers(2)
        prod = cons = None
        try:
            prod = ClusterClient(addrs, n_partitions=4, maxsize=64)
            cons = ClusterClient(addrs, n_partitions=4, maxsize=64)
            N = 22  # deliberately not a batch multiple: pad+mask tail
            for i in range(N):
                assert prod.put(_frame(i))
            assert prod.put_wait(EndOfStream(0, -1, 1, 1), timeout=10)
            seen = []
            for batch in batches_from_queue(cons, batch_size=8, max_wait_s=30.0):
                seen.extend(
                    int(batch.event_idx[j]) for j in range(batch.num_valid)
                )
            assert sorted(seen) == list(range(N))
        finally:
            if prod:
                prod.disconnect()
            if cons:
                cons.disconnect()
            _shutdown(servers)


# ---------------------------------------------------------------------------
# consumer groups: coordinator, fencing, rebalance
# ---------------------------------------------------------------------------

class TestGroupRegistry:
    def test_join_heartbeat_generations_and_fencing(self):
        reg = GroupRegistry(session_timeout_s=30.0)
        r1 = reg.handle({"op": "join", "group": "g", "member": "m1",
                         "n_partitions": 4})
        assert r1["ok"] and r1["members"] == ["m1"]
        gen1 = r1["generation"]
        r2 = reg.handle({"op": "join", "group": "g", "member": "m2",
                         "n_partitions": 4})
        gen2 = r2["generation"]
        assert gen2 > gen1 and r2["members"] == ["m1", "m2"]
        # m1 missed the rebalance: anything it sends at gen1 is FENCED
        hb = reg.handle({"op": "heartbeat", "group": "g", "member": "m1",
                         "generation": gen1})
        assert hb["fenced"] and not hb["ok"]
        drained = reg.handle({"op": "drained", "group": "g", "member": "m1",
                              "generation": gen1, "partition": 0})
        assert drained["fenced"] and not drained["ok"]
        assert reg.handle({"op": "info", "group": "g"})["drained"] == []
        # at the CURRENT generation the same ops succeed
        ok = reg.handle({"op": "drained", "group": "g", "member": "m1",
                         "generation": gen2, "partition": 0})
        assert ok["ok"] and ok["drained"] == [0]

    def test_lease_expiry_bumps_generation(self):
        reg = GroupRegistry(session_timeout_s=0.2)
        reg.handle({"op": "join", "group": "g", "member": "m1",
                    "n_partitions": 2})
        g0 = reg.handle({"op": "join", "group": "g", "member": "m2",
                         "n_partitions": 2})["generation"]
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            time.sleep(0.1)
            r = reg.handle({"op": "join", "group": "g", "member": "m1",
                            "n_partitions": 2})
            if r["members"] == ["m1"]:
                break
        else:
            pytest.fail("expired member never swept")
        assert r["generation"] > g0

    def test_unknown_group_and_bad_requests(self):
        reg = GroupRegistry()
        assert reg.handle({"op": "heartbeat", "group": "nope",
                           "member": "m", "generation": 0})["unknown_group"]
        assert not reg.handle({"op": "join", "group": ""})["ok"]
        assert not reg.handle({"op": "wat", "group": "g"}).get("ok", True) or \
            reg.handle({"op": "join", "group": "g", "member": "m"})["ok"]

    def test_rpc_over_the_wire(self):
        """The 'N' opcode end to end: the registry lives on the server,
        the client speaks JSON through cluster_rpc."""
        servers, addrs = _servers(1)
        try:
            host, _, port = addrs[0].rpartition(":")
            c = TcpQueueClient(host, int(port))
            r = c.cluster_rpc({"op": "join", "group": "wire", "member": "m1",
                               "n_partitions": 2})
            assert r["ok"] and r["members"] == ["m1"]
            r2 = c.cluster_rpc({"op": "heartbeat", "group": "wire",
                                "member": "m1", "generation": r["generation"]})
            assert r2["ok"]
            c.disconnect()
        finally:
            _shutdown(servers)


class TestConsumerGroups:
    def test_two_members_disjoint_partitions_one_eos_each(self):
        servers, addrs = _servers(2)
        clients = []
        try:
            P = 4
            prod = ClusterClient(addrs, n_partitions=P, maxsize=64)
            m1 = ClusterClient(addrs, n_partitions=P, maxsize=64,
                               group="g1", member_id="m1", heartbeat_s=0.2)
            m2 = ClusterClient(addrs, n_partitions=P, maxsize=64,
                               group="g1", member_id="m2", heartbeat_s=0.2)
            clients = [prod, m1, m2]
            N = 32
            for i in range(N):
                assert prod.put(_frame(i))
            assert prod.put_wait(EndOfStream(0, -1, 1, 1), timeout=10)
            got1 = got2 = None
            eos1 = eos2 = 0
            got1, got2 = [], []
            deadline = time.monotonic() + 30.0
            while (not eos1 or not eos2) and time.monotonic() < deadline:
                for it in m1.get_batch_stream(8, timeout=0.2):
                    if is_eos(it):
                        eos1 += 1
                    else:
                        got1.append(it.event_idx)
                for it in m2.get_batch_stream(8, timeout=0.2):
                    if is_eos(it):
                        eos2 += 1
                    else:
                        got2.append(it.event_idx)
            # disjoint coverage, complete union, one aggregated EOS each
            assert sorted(got1 + got2) == list(range(N))
            assert got1 and got2, "a member was starved of partitions"
            assert not (set(got1) & set(got2)), "partitions not disjoint"
            assert eos1 == 1 and eos2 == 1
        finally:
            for c in clients:
                c.disconnect()
            _shutdown(servers)

    def test_member_join_rebalances_and_loses_nothing(self):
        """m1 owns everything, drains a bit; m2 joins mid-stream; the
        union after rebalance is still every frame (duplicates allowed —
        revoked in-flight frames requeue at head), and both finish."""
        servers, addrs = _servers(2)
        clients = []
        try:
            P = 4
            prod = ClusterClient(addrs, n_partitions=P, maxsize=128)
            m1 = ClusterClient(addrs, n_partitions=P, maxsize=128,
                               group="g2", member_id="m1", heartbeat_s=0.1)
            clients = [prod, m1]
            N = 64
            for i in range(N):
                assert prod.put(_frame(i))
            assert prod.put_wait(EndOfStream(0, -1, 1, 1), timeout=10)
            seen = set()
            # m1 alone drains a few batches
            deadline = time.monotonic() + 10.0
            while len(seen) < 8 and time.monotonic() < deadline:
                for it in m1.get_batch_stream(4, timeout=0.3):
                    if not is_eos(it):
                        seen.add(it.event_idx)
            assert len(seen) >= 8
            # m2 joins: generation bumps, m1 rebalances on its next beat
            m2 = ClusterClient(addrs, n_partitions=P, maxsize=128,
                               group="g2", member_id="m2", heartbeat_s=0.1)
            clients.append(m2)
            eos1 = eos2 = 0
            deadline = time.monotonic() + 30.0
            while (not eos1 or not eos2) and time.monotonic() < deadline:
                for it in m1.get_batch_stream(8, timeout=0.2):
                    if is_eos(it):
                        eos1 += 1
                    else:
                        seen.add(it.event_idx)
                for it in m2.get_batch_stream(8, timeout=0.2):
                    if is_eos(it):
                        eos2 += 1
                    else:
                        seen.add(it.event_idx)
            assert seen >= set(range(N)), sorted(set(range(N)) - seen)
            assert eos1 == 1 and eos2 == 1
            assert CLUSTER.stats()["rebalances_total"] >= 1
        finally:
            for c in clients:
                c.disconnect()
            _shutdown(servers)

    def test_member_death_reassigns_with_zero_loss(self):
        """Kill a member WITHOUT leave (sockets die, lease expires): its
        pushed-but-unconsumed frames requeue at head, the survivor
        absorbs its partitions after the lease times out, and the union
        is still complete."""
        servers, addrs = _servers(2)
        clients = []
        try:
            for s in servers:
                s.groups.session_timeout_s = 0.6  # fast lease expiry
            P = 4
            prod = ClusterClient(addrs, n_partitions=P, maxsize=128)
            m1 = ClusterClient(addrs, n_partitions=P, maxsize=128,
                               group="g3", member_id="m1", heartbeat_s=0.15)
            m2 = ClusterClient(addrs, n_partitions=P, maxsize=128,
                               group="g3", member_id="m2", heartbeat_s=0.15)
            clients = [prod, m1]
            N = 48
            for i in range(N):
                assert prod.put(_frame(i))
            assert prod.put_wait(EndOfStream(0, -1, 1, 1), timeout=10)
            seen = set()
            # both drain a little so both are real members with streams
            for _ in range(3):
                for it in m1.get_batch_stream(4, timeout=0.3):
                    if not is_eos(it):
                        seen.add(it.event_idx)
                for it in m2.get_batch_stream(4, timeout=0.3):
                    if not is_eos(it):
                        seen.add(it.event_idx)
            # m2 "crashes": abrupt socket death, no leave, no final ack.
            # A real crash takes the background heartbeat thread with the
            # process — stop it first, else the keepalive would faithfully
            # renew a zombie's lease forever (lease liveness IS process
            # liveness, by design)
            if m2._hb_stop is not None:
                m2._hb_stop.set()
                m2._hb_thread.join(timeout=2.0)
            for c in list(m2._clients.values()):
                c._sock.close()
            if m2._coord is not None:
                m2._coord._sock.close()
            eos1 = 0
            deadline = time.monotonic() + 30.0
            while not eos1 and time.monotonic() < deadline:
                for it in m1.get_batch_stream(8, timeout=0.2):
                    if is_eos(it):
                        eos1 += 1
                    else:
                        seen.add(it.event_idx)
            assert seen >= set(range(N)), sorted(set(range(N)) - seen)
            assert eos1 == 1
        finally:
            for c in clients:
                c.disconnect()
            _shutdown(servers)

    def test_fenced_drain_commit_is_retried_not_dropped(self):
        """Review fix: a drained-commit fenced mid-rebalance is a
        DEFERRAL, not a drop. Deterministic interleaving: a phantom
        member joins behind m1's back (generation bump) right before
        m1's tallies complete; every commit m1 sends is fenced. m1 must
        (a) retry commits for partitions it keeps, (b) re-seed consumed
        markers on partitions it lost, and (c) still produce exactly one
        group EOS once the phantom's lease expires and it reacquires
        everything."""
        servers, addrs = _servers(1)
        prod = m1 = None
        try:
            servers[0].groups.session_timeout_s = 0.8  # phantom expires fast
            P = 2
            prod = ClusterClient(addrs, n_partitions=P, maxsize=32)
            m1 = ClusterClient(addrs, n_partitions=P, maxsize=32,
                               group="g5", member_id="m1", heartbeat_s=0.1)
            N = 8
            for i in range(N):
                assert prod.put(_frame(i))
            assert prod.put_wait(EndOfStream(0, -1, 1, 1), timeout=10)
            with m1._lock:
                m1._ensure_joined()
            # phantom member joins directly on the registry: m1's next
            # commit carries a stale generation and is FENCED
            servers[0].groups.handle({"op": "join", "group": "g5",
                                      "member": "zz-phantom",
                                      "n_partitions": P})
            got, eos = _drain_until_eos(m1, budget_s=30.0)
            assert sorted(set(got)) == list(range(N))
            assert eos == 1
            # the group really did commit every partition (registry view)
            info = servers[0].groups.handle({"op": "info", "group": "g5"})
            assert sorted(info["drained"]) == list(range(P))
        finally:
            if prod:
                prod.disconnect()
            if m1:
                m1.disconnect()
            _shutdown(servers)

    def test_group_name_reuse_starts_a_fresh_drain_epoch(self):
        """Review fix: queue servers are long-lived services — a second
        stream reusing a group name must NOT inherit the first stream's
        drained set (that handed new members an instant bogus EOS and
        silently stranded every new frame). A join into an EMPTY group
        clears the drained state: one name, many runs."""
        servers, addrs = _servers(1)
        clients = []
        try:
            P = 2
            for run in range(2):
                prod = ClusterClient(addrs, n_partitions=P, maxsize=32)
                m = ClusterClient(addrs, n_partitions=P, maxsize=32,
                                  group="reuse", member_id=f"m{run}",
                                  heartbeat_s=0.2)
                clients += [prod, m]
                lo = run * 4
                for i in range(lo, lo + 4):
                    assert prod.put(_frame(i))
                assert prod.put_wait(EndOfStream(0, -1, 1, 1), timeout=10)
                got, eos = _drain_until_eos(m, budget_s=20.0)
                assert sorted(got) == list(range(lo, lo + 4)), (run, got)
                assert eos == 1
                m.disconnect()  # leaves: the group empties between runs
                prod.disconnect()
        finally:
            for c in clients:
                try:
                    c.disconnect()
                except Exception:
                    pass
            _shutdown(servers)

    def test_stale_member_commit_is_fenced_end_to_end(self):
        """Generation fencing through the full stack: a member that
        missed a rebalance gets its drained-commit REJECTED (and its
        session rejoins) — the registry state is never corrupted by a
        stale writer."""
        servers, addrs = _servers(1)
        try:
            m1 = ClusterClient(addrs, n_partitions=2, maxsize=16,
                               group="g4", member_id="m1", heartbeat_s=999)
            with m1._lock:
                m1._ensure_joined()
            stale_gen = m1._session.generation
            # a second member joins behind m1's back -> generation moves
            m2 = ClusterClient(addrs, n_partitions=2, maxsize=16,
                               group="g4", member_id="m2", heartbeat_s=999)
            with m2._lock:
                m2._ensure_joined()
            fenced_before = CLUSTER.stats()["fenced_total"]
            # m1 tries to commit at the stale generation
            assert m1._session.generation == stale_gen
            assert m1._session.commit_drained(0) is False
            assert CLUSTER.stats()["fenced_total"] > fenced_before
            # the registry did NOT record the stale commit...
            info = servers[0].groups.handle({"op": "info", "group": "g4"})
            assert info["drained"] == []
            # ...and the fenced member came back current (rejoined)
            assert m1._session.generation > stale_gen
            assert m1._session.commit_drained(0) is True
            info = servers[0].groups.handle({"op": "info", "group": "g4"})
            assert info["drained"] == [0]
            m1.disconnect()
            m2.disconnect()
        finally:
            _shutdown(servers)


# ---------------------------------------------------------------------------
# failure handling: server death
# ---------------------------------------------------------------------------

class TestServerDeath:
    def test_kill_one_server_mid_stream_loses_zero_frames(self):
        """The ISSUE 7 acceptance shape: kill one of the servers while
        frames are in flight — surviving servers absorb its partitions,
        the producer resends its retained + unacked frames there, and
        every frame is delivered at least once (duplicates allowed)."""
        servers, addrs = _servers(3)
        prod = cons = None
        try:
            P = 4
            prod = ClusterClient(addrs, n_partitions=P, maxsize=64,
                                 retain=256, reconnect_tries=1,
                                 reconnect_base_s=0.05)
            cons = ClusterClient(addrs, n_partitions=P, maxsize=64,
                                 reconnect_tries=1, reconnect_base_s=0.05)
            # victim: the server owning the MOST partitions — ephemeral
            # ports randomize the map per run, and killing a server that
            # happens to own nothing would test nothing
            pmap = prod.partition_map
            victim_addr = max(addrs, key=lambda a: len(pmap.partitions_on(a)))
            victim = servers[addrs.index(victim_addr)]
            assert pmap.partitions_on(victim_addr)
            N = 60
            seen = set()
            for i in range(N):
                assert prod.put_pipelined(
                    _frame(i), deadline=time.monotonic() + 10
                )
                if i == 20:
                    # drain a little, then kill the server that is
                    # holding queued + acked frames
                    for it in cons.get_batch_stream(8, timeout=0.5):
                        if not is_eos(it):
                            seen.add(it.event_idx)
                    victim.shutdown()
            assert prod.flush_puts(time.monotonic() + 30)
            assert prod.put_wait(EndOfStream(0, -1, 1, 1), timeout=20)
            got, eos = _drain_until_eos(cons)
            seen |= set(got)
            missing = set(range(N)) - seen
            assert not missing, f"frames LOST on server death: {sorted(missing)}"
            assert eos == 1
            # both sides observed the same recomputed map
            assert prod.partition_map.version >= 2
            assert cons.partition_map.version >= 2
            stats = CLUSTER.stats()
            assert stats["reassignments_total"] >= 1
        finally:
            if prod:
                prod.disconnect()
            if cons:
                cons.disconnect()
            _shutdown(servers)

    def test_exact_unacked_tail_resends_to_the_new_owner(self):
        """The PR 5 windowed-resend invariant across servers, pinned
        exactly: with retention off, the frames resent to the new owner
        are PRECISELY the tail still unacknowledged after the client
        drained every ack the dead server managed to deliver — no holes
        inside the tail, and no spurious resend of acked frames."""
        servers, addrs = _servers(2, maxsize=8)
        prod = None
        try:
            P = 1  # one partition: full control of what sits where
            prod = ClusterClient(addrs, n_partitions=P, maxsize=8,
                                 retain=0, reconnect_tries=1,
                                 reconnect_base_s=0.05)
            owner = prod.partition_map.assignments[0]
            owner_srv = servers[addrs.index(owner)]
            survivor = servers[1 - addrs.index(owner)]
            # frames 0..2: windowed puts, acks fully drained (known-acked)
            for i in range(3):
                assert prod.put_pipelined(_frame(i), deadline=time.monotonic() + 5)
            assert prod.flush_puts(time.monotonic() + 10)
            # frames 3..10: 3..7 enqueue (acks written but not yet read
            # by the client); 8..10 park server-side against the full
            # queue, their acks never written — the true unacked tail
            for i in range(3, 11):
                assert prod.put_pipelined(_frame(i), deadline=time.monotonic() + 5)
            with prod._lock:
                tail = [r.event_idx for r in prod._clients[0].unacked_puts()]
            assert tail == list(range(3, 11))  # nothing read yet
            # determinism: wait until the owner PROCESSED 3..7 (depth at
            # maxsize) so their acks are committed to the wire before it
            # dies — TCP delivers written data ahead of the FIN
            deadline = time.monotonic() + 5.0
            while owner_srv.depth() < 8 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert owner_srv.depth() == 8
            owner_srv.shutdown()
            # the next flush drains the delivered acks (3..7 become
            # known-acked), hits EOF, fails over, and resends EXACTLY
            # the remaining unacked tail: 8..10. Frames 3..7 died with
            # the server's queue — the exposure `retain` exists to cover
            # (the zero-loss test above runs the same kill WITH retain).
            assert prod.flush_puts(time.monotonic() + 30)
            host, _, port = addrs[1 - addrs.index(owner)].rpartition(":")
            drain = TcpQueueClient(
                host, int(port), namespace="default",
                queue_name=partition_queue_name("shared_queue", 0),
            )
            redelivered = sorted(
                r.event_idx for r in drain.get_batch(64, timeout=1.0)
            )
            assert redelivered == [8, 9, 10], redelivered
            assert survivor.depth() == 0  # nothing else was resent
            drain.disconnect()
        finally:
            if prod:
                prod.disconnect()
            _shutdown(servers)

    def test_eos_broadcast_survives_server_death_via_retention(self):
        """Review fix: EndOfStream markers ride the producer retention
        buffer like frames — a server that dies AFTER acking the EOS
        broadcast must not take its partitions' end-of-stream with it.
        The producer's next partition op fails over and resends retained
        frames AND the marker; the consumer still terminates."""
        servers, addrs = _servers(2)
        prod = cons = None
        try:
            P = 2
            qname = _balanced_queue_name(addrs, P, per_server_cap=1)
            prod = ClusterClient(addrs, queue_name=qname, n_partitions=P,
                                 maxsize=16, retain=16, reconnect_tries=1,
                                 reconnect_base_s=0.05)
            N = 6
            for i in range(N):
                assert prod.put(_frame(i))
            assert prod.put_wait(EndOfStream(0, -1, 1, 1), timeout=10)
            # the broadcast is fully acked; NOW a server dies with its
            # queued frames + marker
            pmap = prod.partition_map
            victim_addr = max(addrs, key=lambda a: len(pmap.partitions_on(a)))
            servers[addrs.index(victim_addr)].shutdown()
            # any partition op on the live producer triggers failover +
            # retained resend (frames AND the EOS marker)
            prod.size()
            cons = ClusterClient(addrs, queue_name=qname, n_partitions=P,
                                 maxsize=16, reconnect_tries=1,
                                 reconnect_base_s=0.05)
            got, eos = _drain_until_eos(cons, budget_s=20.0)
            assert set(got) >= set(range(N)), sorted(set(range(N)) - set(got))
            assert eos == 1
        finally:
            if prod:
                prod.disconnect()
            if cons:
                cons.disconnect()
            _shutdown(servers)

    def test_all_servers_dead_raises(self):
        servers, addrs = _servers(2)
        prod = None
        try:
            prod = ClusterClient(addrs, n_partitions=2, maxsize=16,
                                 reconnect_tries=1, reconnect_base_s=0.05)
            assert prod.put(_frame(0))
            _shutdown(servers)
            with pytest.raises(TransportClosed):
                for i in range(1, 8):
                    prod.put(_frame(i))
        finally:
            if prod:
                prod.disconnect()


# ---------------------------------------------------------------------------
# the acceptance pin: deterministic message-count scaling proxy
# ---------------------------------------------------------------------------

def _balanced_queue_name(addrs, P=8, per_server_cap=3):
    """Search a queue name whose rendezvous map spreads partitions with
    no server above ``per_server_cap`` — deterministic given the ports,
    and the capacity precondition the proxy asserts against."""
    for i in range(512):
        name = f"scaling_q{i}"
        m = PartitionMap.compute(addrs, name, P)
        if max(len(m.partitions_on(a)) for a in addrs) <= per_server_cap:
            return name
    raise AssertionError("no balanced map found — hashring is degenerate")


class _RelayCore:
    """Saturated-relay model shared with bench cluster-scaling: one
    token bucket per server caps its queue ops/s — the regime where the
    single Python relay core is the bottleneck (ROADMAP item 2), which
    a 2-core loopback box cannot otherwise reach."""

    def __init__(self, ops_per_s):
        self._interval = 1.0 / ops_per_s
        self._next = 0.0
        self._lock = threading.Lock()

    def tick(self, n=1):
        with self._lock:
            now = time.monotonic()
            t = max(self._next, now)
            self._next = t + n * self._interval
        delay = t - now
        if delay > 0:
            time.sleep(delay)


class _ThrottledRing(RingBuffer):
    def __init__(self, maxsize, core, name=None):
        super().__init__(maxsize, name=name)
        self._core = core

    def put(self, item):
        self._core.tick()
        return super().put(item)

    def get_batch(self, max_items, timeout=0.0):
        items = super().get_batch(max_items, timeout)
        if items:
            self._core.tick(len(items))
        return items


@pytest.mark.slow
class TestClusterScalingWallClock:
    """The wall-clock half of the ISSUE 7 acceptance, slow-marked with
    best-of-retries per the PR 5 convention (the GIL quantum on this
    2-core box episodically dominates); tier-1 keeps the deterministic
    message-count proxy below."""

    def _run(self, n_servers, n_frames=400, ops_per_s=250.0):
        servers = []
        for _ in range(n_servers):
            core = _RelayCore(ops_per_s)
            servers.append(
                TcpQueueServer(
                    _ThrottledRing(256, core), host="127.0.0.1", maxsize=256,
                    queue_factory=(
                        lambda ns, name, maxsize, _c=core:
                        _ThrottledRing(maxsize, _c, name=f"{ns}__{name}")
                    ),
                ).serve_background()
            )
        addrs = [f"127.0.0.1:{s.port}" for s in servers]
        prod = cons = None
        try:
            qname = _balanced_queue_name(addrs, 8, per_server_cap=8 // n_servers + 1)
            prod = ClusterClient(addrs, queue_name=qname, n_partitions=8,
                                 maxsize=256)
            cons = ClusterClient(addrs, queue_name=qname, n_partitions=8,
                                 maxsize=256)

            def produce():
                for i in range(n_frames):
                    assert prod.put_pipelined(
                        _frame(i), deadline=time.monotonic() + 60
                    )
                prod.flush_puts(time.monotonic() + 60)
                prod.put_wait(EndOfStream(0, -1, 1, 1), timeout=60)

            t = threading.Thread(target=produce, daemon=True)
            t0 = time.monotonic()
            t.start()
            got, eos = _drain_until_eos(cons, budget_s=120.0, batch=32)
            dt = time.monotonic() - t0
            t.join(timeout=10.0)
            assert sorted(set(got)) == list(range(n_frames))
            assert eos == 1
            return n_frames / dt
        finally:
            if prod:
                prod.disconnect()
            if cons:
                cons.disconnect()
            _shutdown(servers)

    def test_four_servers_at_least_2x_one_server_under_relay_model(self):
        best = 0.0
        for _ in range(2):  # best-of-retries: GIL-quantum flake armor
            fps1 = self._run(1)
            fps4 = self._run(4)
            best = max(best, fps4 / fps1)
            if best >= 2.0:
                break
        assert best >= 2.0, (
            f"4-server aggregate only {best:.2f}x the 1-server figure "
            f"under the saturated-relay model (bench measured 2.6x)"
        )


class TestClusterScalingProxy:
    def test_four_servers_balanced_capacity_and_complete_delivery(self):
        """ISSUE 7 acceptance, deterministic proxy form (the wall-clock
        2x row lives in bench cluster-scaling): with 4 servers and a
        balanced 8-partition map, round-robin placement puts <= 3/8 of
        the stream on any one server — aggregate capacity >= 2x any
        single server at equal service rates — and the merged streams
        deliver every message exactly (no crashes -> no duplicates)."""
        servers, addrs = _servers(4)
        prod = cons = None
        try:
            P = 8
            qname = _balanced_queue_name(addrs, P)
            prod = ClusterClient(addrs, queue_name=qname, n_partitions=P,
                                 maxsize=64)
            cons = ClusterClient(addrs, queue_name=qname, n_partitions=P,
                                 maxsize=64)
            N = 64  # 8 per partition, exactly, by round-robin
            for i in range(N):
                assert prod.put(_frame(i))
            # message-count proxy: hosted frames per server == the map's
            # partition share x N/P, exactly (deterministic placement)
            pmap = prod.partition_map
            for s, addr in zip(servers, addrs):
                expect = len(pmap.partitions_on(addr)) * (N // P)
                assert s.depth() == expect, (addr, s.depth(), expect)
            shares = [s.depth() / N for s in servers]
            assert max(shares) <= 3 / 8, shares  # >= 2x single-server capacity
            assert sum(1 for sh in shares if sh > 0) >= 3
            assert prod.put_wait(EndOfStream(0, -1, 1, 1), timeout=10)
            got, eos = _drain_until_eos(cons)
            assert sorted(got) == list(range(N))  # exactly once, nothing lost
            assert eos == 1
        finally:
            if prod:
                prod.disconnect()
            if cons:
                cons.disconnect()
            _shutdown(servers)
