"""ISSUE 16 — continuous profiling plane.

Covers the acceptance rows: the sampler's steady state allocates
nothing (``sys.getallocatedblocks``, per repo tradition); sampling at
97 Hz costs within a generous unit-test bound of the unprofiled run
(the 3% gate lives in bench.py where the box is quiet); on a LIVE
3-thread relay ≥80% of on-CPU samples bill to the canonical stage
vocabulary; collapsed/speedscope exports round-trip; ``prof_merge``
aligns two spools with wildly different monotonic epochs onto one
wallclock axis; the CLI flags, federation payload, flight-dump block,
evloop busy-fraction, and the bench baseline rule all exist.
"""

import argparse
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from psana_ray_tpu.infeed.batcher import batches_from_queue
from psana_ray_tpu.obs import prof_merge
from psana_ray_tpu.obs.profiling import (
    DEFAULT_HZ,
    FlameSampler,
    ProfTelemetry,
    StackTrie,
    add_profile_args,
    configure_profiling_from_args,
    default_profiler,
    profile_summary,
    profile_top,
    start_default_profiler,
    stop_default_profiler,
)
from psana_ray_tpu.obs.profiling.export import (
    collapsed_lines,
    load_spool,
    parse_collapsed,
    speedscope_doc,
    spool_doc,
    write_spool,
)
from psana_ray_tpu.obs.profiling.stagetag import (
    N_TAGS,
    TAG_BATCH,
    TAG_DEVICE_PUT,
    TAG_NAMES,
    TAG_UNTAGGED,
    current_tag,
    set_stage,
    stage_region,
    swap_stage,
)
from psana_ray_tpu.obs.registry import MetricsRegistry, federation_payload
from psana_ray_tpu.obs.stages import STAGES
from psana_ray_tpu.records import EndOfStream, FrameRecord
from psana_ray_tpu.transport.ring import RingBuffer
from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_default_profiler():
    """Each test starts and ends with the process-global profiler off
    (the CLI tests start one; it must not leak into the next test)."""
    stop_default_profiler()
    yield
    stop_default_profiler()


def _rec(i, shape=(2, 32, 32)):
    return FrameRecord(0, i, np.full(shape, i % 7, np.uint16), 9.0)


# ---------------------------------------------------------------------------
# 1. vocabulary + stage tags
# ---------------------------------------------------------------------------

class TestStageTags:
    def test_tag_names_pin_the_canonical_stage_vocabulary(self):
        """TAG_NAMES[1:] IS obs.stages.STAGES — the profiler bills to
        the exact vocabulary the latency histograms speak; drift here
        would silently fork the stage taxonomy."""
        assert tuple(TAG_NAMES[1:]) == tuple(STAGES)
        assert TAG_NAMES[TAG_UNTAGGED] == "untagged"
        assert N_TAGS == len(STAGES) + 1

    def test_swap_and_restore(self):
        assert current_tag() == TAG_UNTAGGED
        prev = swap_stage(TAG_BATCH)
        assert prev == TAG_UNTAGGED
        assert current_tag() == TAG_BATCH
        set_stage(prev)
        assert current_tag() == TAG_UNTAGGED

    def test_stage_region_nests_and_unwinds(self):
        with stage_region("batch"):
            assert current_tag() == TAG_BATCH
            with stage_region("device_put"):
                assert current_tag() == TAG_DEVICE_PUT
            assert current_tag() == TAG_BATCH
        assert current_tag() == TAG_UNTAGGED

    def test_stage_region_delegates_to_inner_and_unknown_stage_is_untagged(self):
        calls = []

        class Inner:
            def __enter__(self):
                calls.append("enter")

            def __exit__(self, *exc):
                calls.append("exit")
                return False

        with stage_region("no_such_stage", Inner()):
            assert current_tag() == TAG_UNTAGGED  # unknown name never raises
        assert calls == ["enter", "exit"]
        assert current_tag() == TAG_UNTAGGED


# ---------------------------------------------------------------------------
# 2. trie: zero-alloc steady state, bounded overflow
# ---------------------------------------------------------------------------

class TestStackTrie:
    def test_sample_is_allocation_free_steady_state(self):
        """The zero-alloc-on-sample contract (same pin as SeriesRing
        /TimeSeriesStore): folding a warmed stack allocates nothing."""
        trie = StackTrie()
        f = sys._getframe()
        for _ in range(200):  # warm: every path + code key seen
            trie.sample(f, True, TAG_BATCH)
            trie.sample(f, False, TAG_UNTAGGED)
        before = sys.getallocatedblocks()
        for _ in range(10_000):
            trie.sample(f, True, TAG_BATCH)
        grew = sys.getallocatedblocks() - before
        assert grew <= 16, f"trie.sample allocated ({grew} blocks / 10k samples)"
        assert trie.samples_total == 400 + 10_000

    def test_overflow_bills_deepest_prefix_never_grows_past_cap(self):
        trie = StackTrie(max_nodes=N_TAGS + 2, max_depth=16)

        def deep(n):
            if n == 0:
                trie.sample(sys._getframe(), True, TAG_UNTAGGED)
            else:
                deep(n - 1)

        for _ in range(8):
            deep(10)
        assert trie.n_nodes <= N_TAGS + 2
        assert trie.overflow_total > 0
        assert trie.samples_total == 8  # degraded profile, counted samples

    def test_rows_and_hot_frames_read_back(self):
        trie = StackTrie()
        f = sys._getframe()
        for _ in range(5):
            trie.sample(f, True, TAG_BATCH)
        rows = trie.rows()
        assert rows and all(r["stage"] == "batch" for r in rows)
        assert sum(r["on"] for r in rows) == 5
        hot = trie.hot_frames(4)
        assert hot and hot[0]["self"] == 5
        assert "test_profiling" in hot[0]["frame"]
        assert trie.stage_totals()["batch"]["on"] == 5


# ---------------------------------------------------------------------------
# 3. sampler: discrimination, sampler-path zero-alloc, overhead
# ---------------------------------------------------------------------------

class TestFlameSampler:
    def test_hz_zero_rejected(self):
        with pytest.raises(ValueError):
            FlameSampler(hz=0.0)

    def test_sample_once_is_allocation_free_steady_state(self):
        """The whole sampling path — _current_frames snapshot, procfs
        pread, tag lookup, trie fold — allocates nothing live after
        warmup (transient snapshot dict/bytes are freed within the
        call and don't count as growth)."""
        s = FlameSampler(hz=97.0, process="pin", register=False)
        s._own_ident = -1  # don't skip the calling thread
        for _ in range(50):  # warm: register threads, open fds, grow trie
            s._sample_once()
        before = sys.getallocatedblocks()
        for _ in range(1000):
            s._sample_once()
        grew = sys.getallocatedblocks() - before
        assert grew <= 16, f"_sample_once allocated ({grew} blocks / 1k calls)"

    def test_on_cpu_vs_waiting_discrimination_live(self):
        """A spinning tagged thread bills mostly on-CPU; a sleeping
        tagged thread bills mostly waiting. 97 Hz period (10.3ms) sits
        above the 100 Hz USER_HZ accounting tick, so a busy thread
        advances its CPU clock nearly every sample."""
        stop = threading.Event()

        def burner():
            set_stage(TAG_BATCH)
            x = 0
            while not stop.is_set():
                x += 1

        def sleeper():
            set_stage(TAG_DEVICE_PUT)
            stop.wait(5.0)

        s = FlameSampler(hz=97.0, process="disc", register=False)
        tb = threading.Thread(target=burner, daemon=True)
        ts = threading.Thread(target=sleeper, daemon=True)
        tb.start(), ts.start()
        s.start()
        time.sleep(1.5)
        s.stop(write_spool=False)
        stop.set()
        tb.join(timeout=5), ts.join(timeout=5)
        totals = s.trie.stage_totals()
        burn = totals.get("batch", {"on": 0, "off": 0})
        slp = totals.get("device_put", {"on": 0, "off": 0})
        assert burn["on"] + burn["off"] >= 50  # ~145 expected at 97 Hz
        assert burn["on"] > 0.6 * (burn["on"] + burn["off"]), totals
        assert slp["off"] > 0.6 * (slp["on"] + slp["off"]), totals
        assert s.trie.samples_total == s.trie.on_cpu_total + s.trie.waiting_total

    def test_overhead_within_unit_test_bound(self):
        """A/B the sampler against a fixed CPU-bound workload. The real
        acceptance (3%) is measured in bench.py's quiet A/B harness
        (host_datapath_prof_delta_pct); this unit test pins a generous
        25% so a pathological regression (per-sample allocation, lock
        on the hot path) fails fast anywhere."""
        payload = np.random.default_rng(0).integers(
            0, 1000, (4, 64, 64), dtype=np.uint16
        )

        def work():
            t0 = time.perf_counter()
            for i in range(300):
                r = FrameRecord(0, i, payload, 9.0)
                FrameRecord.from_bytes(r.to_bytes())
            return time.perf_counter() - t0

        work()  # warm caches/allocator
        base = min(work() for _ in range(5))
        s = FlameSampler(hz=97.0, process="ab", register=False).start()
        try:
            prof = min(work() for _ in range(5))
        finally:
            s.stop(write_spool=False)
        assert s.trie.samples_total > 0  # it really sampled during B
        # best-of-5 + a wide bound: shared CI boxes jitter more than the
        # sampler costs, and a genuine regression (per-sample allocation,
        # hot-path lock) shows up as 2-10x, not 25%
        assert prof <= base * 1.25 + 0.05, (
            f"97 Hz sampling cost {100 * (prof / base - 1):.1f}% "
            f"(base {base * 1e3:.1f}ms, profiled {prof * 1e3:.1f}ms)"
        )


# ---------------------------------------------------------------------------
# 4. stage attribution on a live relay (the ISSUE 16 acceptance row)
# ---------------------------------------------------------------------------

class TestLiveRelayAttribution:
    def test_most_busy_samples_bill_to_known_stages(self):
        """producer thread -> TCP queue server (evloop) -> consumer
        drain, profiled end to end: ≥80% of on-CPU samples carry a
        stage tag from the canonical vocabulary (put_wait tags enqueue,
        the drain loop tags dequeue/batch, the evloop tags dispatch)."""
        # pre-built OUTSIDE the profiled window (creation is untagged);
        # 256 KB/frame makes the relay CPU-bound in encode/copy/decode,
        # and cycling the list keeps it busy long enough (~2s) for the
        # 97 Hz sampler to accumulate a judgeable on-CPU population
        records = [_rec(i, shape=(8, 128, 128)) for i in range(300)]
        n = len(records) * 5
        srv = TcpQueueServer(RingBuffer(64), host="127.0.0.1").serve_background()
        sampler = FlameSampler(hz=97.0, process="relay", register=False)

        def produce():
            c = TcpQueueClient("127.0.0.1", srv.port)
            try:
                for i in range(n):
                    if not c.put_wait(records[i % len(records)], timeout=30):
                        return
                c.put_wait(EndOfStream(total_events=n), timeout=30)
            finally:
                c.disconnect()

        consumer = TcpQueueClient("127.0.0.1", srv.port)
        sampler.start()
        prod = threading.Thread(target=produce, daemon=True)
        prod.start()
        seen = 0
        try:
            for batch in batches_from_queue(
                consumer, batch_size=16, max_wait_s=60, prefer_stream=False
            ):
                seen += batch.num_valid
        finally:
            sampler.stop(write_spool=False)
            prod.join(timeout=30)
            consumer.disconnect()
            srv.shutdown()
        assert seen == n
        totals = sampler.trie.stage_totals()
        on_known = sum(
            t["on"] for name, t in totals.items() if name != "untagged"
        )
        on_total = sampler.trie.on_cpu_total
        assert on_total >= 20, f"too few busy samples to judge: {totals}"
        frac = on_known / on_total
        assert frac >= 0.8, (
            f"only {100 * frac:.0f}% of {on_total} on-CPU samples billed "
            f"to known stages: {totals}"
        )
        # the decomposition reaches more than one stage on a real relay
        assert len([s for s in totals if s != "untagged"]) >= 2, totals


# ---------------------------------------------------------------------------
# 5. exports: collapsed / speedscope round trip, spool write+load
# ---------------------------------------------------------------------------

class TestExports:
    def _trie(self):
        trie = StackTrie()
        f = sys._getframe()
        for _ in range(7):
            trie.sample(f, True, TAG_BATCH)
        for _ in range(3):
            trie.sample(f, False, TAG_UNTAGGED)
        return trie

    def test_collapsed_round_trip(self):
        trie = self._trie()
        lines = collapsed_lines(trie)
        parsed = parse_collapsed(lines)
        assert parsed and sum(c for _, c in parsed) == trie.on_cpu_total
        for stack, _ in parsed:
            assert stack[0] == "batch"  # stage rides as the first frame
            assert any("test_profiling" in fr for fr in stack[1:])
        waiting = parse_collapsed(collapsed_lines(trie, waiting=True))
        assert sum(c for _, c in waiting) == trie.waiting_total

    def test_speedscope_doc_shape(self):
        trie = self._trie()
        doc = speedscope_doc(trie, name="unit")
        prof = doc["profiles"][0]
        assert prof["type"] == "sampled"
        assert sum(prof["weights"]) == prof["endValue"] == trie.on_cpu_total
        nframes = len(doc["shared"]["frames"])
        for stack in prof["samples"]:
            assert all(0 <= i < nframes for i in stack)
            assert doc["shared"]["frames"][stack[0]]["name"].startswith("stage: ")
        json.dumps(doc)  # serialisable as-is

    def test_spool_write_load_round_trip(self, tmp_path):
        s = FlameSampler(hz=50.0, process="unit", register=False).start()
        time.sleep(0.25)
        s.stop(write_spool=False)
        path = write_spool(s, directory=str(tmp_path))
        assert path.endswith(f"unit-{os.getpid()}.prof.json")
        doc = load_spool(path)
        assert doc["kind"] == "psana_ray_tpu.prof_spool"
        assert doc["meta"]["process"] == "unit" and doc["meta"]["hz"] == 50.0
        assert doc["totals"]["samples"] == s.trie.samples_total
        assert len(doc["anchors"]) >= 2  # start anchor + dump-time anchor
        bogus = tmp_path / "not_a_spool.json"
        bogus.write_text("{}")
        with pytest.raises(ValueError):
            load_spool(str(bogus))


# ---------------------------------------------------------------------------
# 6. prof_merge: clock alignment across monotonic epochs, CLI
# ---------------------------------------------------------------------------

def _spool_file(tmp_path, process, pid, wall0, mono0, leaf_on):
    """A handcrafted spool: one stack, two cpu_frac ticks, one anchor."""
    doc = {
        "kind": "psana_ray_tpu.prof_spool",
        "version": 1,
        "meta": {
            "process": process, "pid": pid, "hz": 97.0,
            "start_wall": wall0, "start_mono": mono0,
        },
        "anchors": [{"wall": wall0, "mono": mono0}],
        "totals": {
            "samples": leaf_on + 2, "on_cpu": leaf_on, "waiting": 2,
            "nodes": 9, "overflow": 0,
        },
        "stage_totals": {"batch": {"on": leaf_on, "off": 2}},
        "stage_cpu_ms": {"batch": leaf_on * (1000.0 / 97.0)},
        "cpu_series": [[mono0 + 1.0, 0.5], [mono0 + 2.0, 0.75]],
        "stacks": [
            {"stage": "batch", "frames": ["a.py:outer:1", "a.py:hot:9"],
             "on": leaf_on, "off": 2},
        ],
    }
    path = tmp_path / f"{process}-{pid}.prof.json"
    path.write_text(json.dumps(doc))
    return str(path)


class TestProfMerge:
    def test_merge_aligns_two_spools_with_distinct_mono_epochs(self, tmp_path):
        """Golden: two processes whose monotonic clocks started ~4900s
        apart but whose wallclocks nearly agree merge onto ONE unified
        timeline — the counter events land within the wallclock skew,
        not the monotonic epoch gap."""
        a = _spool_file(tmp_path, "producer", 11, wall0=1000.0, mono0=100.0,
                        leaf_on=3)
        b = _spool_file(tmp_path, "consumer", 22, wall0=1001.0, mono0=5000.0,
                        leaf_on=5)
        doc = prof_merge.merge([str(tmp_path)])
        prof = doc["profile"]
        assert len(prof["processes"]) == 2
        assert prof["on_cpu_total"] == 8 and prof["samples_total"] == 12
        # hot frames aggregate by LEAF (self time) across processes
        assert prof["hot"][0] == {"frame": "a.py:hot:9", "self": 8}
        assert prof["stage_cpu_ms"]["batch"] == pytest.approx(
            8 * (1000.0 / 97.0)
        )
        counters = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
        assert len(counters) == 4 and all(
            e["name"] == "cpu_frac" for e in counters
        )
        by_pid = {}
        for e in counters:
            by_pid.setdefault(e["pid"], []).append(e["ts"])
        (first_a, first_b) = (min(ts) for ts in by_pid.values())
        # unified axis: mono 101 @ offset +900 vs mono 5001 @ offset
        # -3999 both land near wall 1001-1002 — within 5s, not 4900s
        assert abs(first_a - first_b) < 5e6, (first_a, first_b)
        names = [e for e in doc["traceEvents"] if e.get("ph") == "M"]
        assert {e["args"]["name"] for e in names} == {
            "prof producer:11", "prof consumer:22"
        }
        del a, b

    def test_merged_collapsed_prefixes_process(self, tmp_path):
        _spool_file(tmp_path, "producer", 11, 1000.0, 100.0, leaf_on=3)
        lines = prof_merge.merged_collapsed([str(tmp_path)])
        assert lines == ["producer:11;batch;a.py:outer:1;a.py:hot:9 3"]
        ss = prof_merge.merged_speedscope([str(tmp_path)])
        assert ss["profiles"][0]["endValue"] == 3

    def test_cli_main_writes_all_artifacts(self, tmp_path, capsys):
        _spool_file(tmp_path, "producer", 11, 1000.0, 100.0, leaf_on=3)
        _spool_file(tmp_path, "consumer", 22, 1001.0, 5000.0, leaf_on=5)
        out = tmp_path / "merged.json"
        folded = tmp_path / "cluster.folded"
        ss = tmp_path / "cluster.ss.json"
        rc = prof_merge.main([
            str(tmp_path), "--out", str(out),
            "--collapsed", str(folded), "--speedscope", str(ss),
        ])
        assert rc == 0
        assert json.loads(out.read_text())["profile"]["samples_total"] == 12
        assert len(folded.read_text().splitlines()) == 2
        assert json.loads(ss.read_text())["profiles"][0]["endValue"] == 8
        assert "merged 2 process profile(s)" in capsys.readouterr().out

    def test_cli_main_no_spools_is_a_clean_error(self, tmp_path):
        assert prof_merge.main([str(tmp_path / "empty")]) == 1


# ---------------------------------------------------------------------------
# 7. cost model + the `prof` source
# ---------------------------------------------------------------------------

class TestCostModel:
    def test_per_frame_cost_from_injected_counters(self):
        frames = [0]
        nbytes = [0]
        tel = ProfTelemetry(frames_fn=lambda: frames[0], bytes_fn=lambda: nbytes[0])
        tel.tick_cost_model(now=10.0)  # baseline tick
        deadline = time.process_time() + 0.08  # burn ≥8 os.times ticks
        x = 0
        while time.process_time() < deadline:
            x += 1
        frames[0], nbytes[0] = 200, 1 << 20
        tel.tick_cost_model(now=11.0)
        assert tel.cpu_frac > 0.0
        assert tel.cpu_ns_per_frame > 0.0
        assert tel.py_bytes_per_frame == pytest.approx((1 << 20) / 200.0)
        assert tel.ticks_total == 2 and tel.frames_seen == 200
        assert len(tel.cpu_timeline()) == 2
        snap = tel.snapshot()
        assert snap["enabled"] == 0  # no sampler attached
        for k in ("cpu_frac", "cpu_ns_per_frame", "py_bytes_per_frame"):
            assert isinstance(snap[k], float)

    def test_prof_source_registers_on_the_default_registry(self):
        reg = MetricsRegistry.default()
        assert "prof" not in reg.snapshot()
        s = start_default_profiler(hz=50.0, process="unit")
        try:
            assert default_profiler() is s
            assert start_default_profiler(hz=999.0) is s  # idempotent
            snap = reg.snapshot()["prof"]
            assert snap["enabled"] == 1 and snap["hz"] == 50.0
        finally:
            stop_default_profiler()
        assert "prof" not in reg.snapshot()
        assert default_profiler() is None


# ---------------------------------------------------------------------------
# 8. surfaces: federation, flight dumps, evloop busy fraction, CLI, bench
# ---------------------------------------------------------------------------

class TestSurfaces:
    def test_federation_payload_profile_block(self):
        assert federation_payload()["profile"] is None  # off costs nothing
        start_default_profiler(hz=50.0, process="fed")
        try:
            time.sleep(0.15)
            prof = federation_payload()["profile"]
            assert prof is not None and prof["hz"] == 50.0
            for k in ("samples", "on_cpu", "cpu_frac", "cpu_ns_per_frame",
                      "hot", "stage_cpu_ms"):
                assert k in prof
            json.dumps(prof)  # strings ride OUTSIDE the numeric metrics
        finally:
            stop_default_profiler()

    def test_profile_top_and_summary_none_when_off(self):
        assert profile_top() is None
        assert profile_summary() is None

    def test_flight_dump_embeds_profile_top(self, tmp_path):
        from psana_ray_tpu.obs.flight import FlightRecorder

        fl = FlightRecorder()
        fl.record("unit_event", k=1)
        p_off = fl.dump("off", path=str(tmp_path / "off.json"), force=True)
        assert json.loads(open(p_off).read())["profile_top"] is None
        start_default_profiler(hz=50.0, process="fl")
        try:
            time.sleep(0.15)
            p_on = fl.dump("on", path=str(tmp_path / "on.json"), force=True)
            top = json.loads(open(p_on).read())["profile_top"]
            assert top["samples"] > 0 and "hot" in top and "stage_cpu_ms" in top
        finally:
            stop_default_profiler()

    def test_evloop_busy_fraction(self):
        from psana_ray_tpu.transport.evloop import EvLoopTelemetry

        t = EvLoopTelemetry()
        assert t.stats()["busy_frac"] == 0.0  # no passes yet: defined, idle
        t.loop_pass(10.0, select_ms=10.0)
        s = t.stats()
        assert s["busy_frac"] == pytest.approx(0.5)
        assert 0.0 < s["busy_frac_ewma"] <= 0.5
        t.loop_pass(30.0, select_ms=0.0)
        assert t.stats()["busy_frac"] == pytest.approx(0.8)  # 40 / 50

    def test_cli_args_plumb(self):
        p = argparse.ArgumentParser()
        add_profile_args(p)
        a = p.parse_args([])
        assert a.profile_hz == DEFAULT_HZ and a.profile_dir is None
        assert configure_profiling_from_args(
            p.parse_args(["--profile_hz", "0"])
        ) is None
        s = configure_profiling_from_args(
            p.parse_args(["--profile_hz", "53"]), process="unit"
        )
        try:
            assert s is not None and s.hz == 53.0 and s.running
        finally:
            stop_default_profiler()
        # the consumer CLI already owns --profile_dir (device traces):
        # add_profile_args must tolerate the pre-existing flag
        q = argparse.ArgumentParser()
        q.add_argument("--profile_dir", default="existing")
        add_profile_args(q)
        assert q.parse_args([]).profile_dir == "existing"
        # every long-running CLI wires the shared pair
        for mod in ("producer.py", "consumer.py", "queue_server.py", "sfx.py"):
            src = open(os.path.join(REPO_ROOT, "psana_ray_tpu", mod)).read()
            assert "add_profile_args(" in src, mod
            assert "configure_profiling_from_args(" in src, mod

    def test_queue_server_help_advertises_the_flags(self):
        out = subprocess.run(
            [sys.executable, "-m", "psana_ray_tpu.queue_server", "--help"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0
        assert "--profile_hz" in out.stdout and "--profile_dir" in out.stdout

    def test_bench_baseline_gates_cpu_ns_per_frame(self):
        sys.path.insert(0, REPO_ROOT)
        try:
            from bench import compare_baseline
        finally:
            sys.path.remove(REPO_ROOT)
        base = {"host_datapath_cpu_ns_per_frame": 1000.0}
        bad = compare_baseline({"host_datapath_cpu_ns_per_frame": 1300.0}, base)
        assert [r["rule"] for r in bad] == ["cpu_ns_per_frame"]
        assert bad[0]["direction"] == "lower"
        ok = compare_baseline({"host_datapath_cpu_ns_per_frame": 900.0}, base)
        assert ok == []
        within = compare_baseline({"host_datapath_cpu_ns_per_frame": 1100.0}, base)
        assert within == []  # 10% < the 15% tolerance


# ---------------------------------------------------------------------------
# 9. spool -> prof_merge over a REAL sampler run (end-to-end smoke)
# ---------------------------------------------------------------------------

class TestEndToEndSpool:
    def test_sampler_spool_merges(self, tmp_path):
        s = FlameSampler(
            hz=97.0, process="e2e", spool_dir=str(tmp_path), register=False
        ).start()
        stop = threading.Event()

        def burner():
            set_stage(TAG_BATCH)
            x = 0
            while not stop.is_set():
                x += 1

        t = threading.Thread(target=burner, daemon=True)
        t.start()
        time.sleep(0.6)
        stop.set()
        t.join(timeout=5)
        s.stop()  # writes the spool
        doc = prof_merge.merge([str(tmp_path)])
        prof = doc["profile"]
        assert len(prof["processes"]) == 1
        assert prof["processes"][0]["process"] == f"e2e:{os.getpid()}"
        assert prof["samples_total"] == s.trie.samples_total > 0
        assert "batch" in prof["stage_cpu_ms"]
        assert prof["hot"], "a busy run must surface hot frames"
