"""Calibration ops vs a plain-numpy oracle; Pallas kernel vs XLA path."""

import numpy as np
import pytest

import jax.numpy as jnp

from psana_ray_tpu.config import RetrievalMode
from psana_ray_tpu.ops import apply_mask, calibrate, common_mode, fused_calibrate
from psana_ray_tpu.ops.calib import gain_correct, subtract_pedestal
from psana_ray_tpu.sources import SyntheticSource


@pytest.fixture(scope="module")
def frame_set():
    src = SyntheticSource(num_events=3, detector_name="epix100", seed=3)
    raws = np.stack([src.event(i, RetrievalMode.RAW)[0] for i in range(3)])
    return {
        "raw": raws,  # [3, 1, 704, 768]
        "pedestal": src.pedestal(),
        "gain": src.gain_map(),
        "mask": src.create_bad_pixel_mask(),
        "src": src,
    }


def test_apply_mask_parity():
    # reference semantics: np.where(mask, data, 0) (producer.py:92-95)
    x = np.random.default_rng(0).normal(size=(2, 4, 8)).astype(np.float32)
    mask = (np.random.default_rng(1).random((2, 4, 8)) > 0.3).astype(np.uint8)
    out = np.asarray(apply_mask(jnp.asarray(x), jnp.asarray(mask)))
    np.testing.assert_array_equal(out, np.where(mask, x, 0))


def test_apply_mask_broadcasts_over_batch():
    x = np.ones((5, 2, 4, 8), np.float32)
    mask = np.zeros((2, 4, 8), np.uint8)
    assert np.asarray(apply_mask(jnp.asarray(x), jnp.asarray(mask))).sum() == 0


def test_pedestal_and_gain():
    x = np.full((1, 4, 8), 110.0, np.float32)
    ped = np.full((1, 4, 8), 100.0, np.float32)
    gain = np.full((1, 4, 8), 2.0, np.float32)
    out = gain_correct(subtract_pedestal(jnp.asarray(x), jnp.asarray(ped)), jnp.asarray(gain))
    np.testing.assert_allclose(np.asarray(out), 5.0)


@pytest.mark.parametrize("algorithm", ["mean", "median"])
def test_common_mode_removes_offset(algorithm):
    # background-only panels with a known per-panel offset -> exact removal
    rng = np.random.default_rng(0)
    base = rng.normal(0, 1, size=(2, 16, 128)).astype(np.float32)
    offsets = np.array([5.0, -3.0], np.float32)[:, None, None]
    corrected = np.asarray(common_mode(jnp.asarray(base + offsets), threshold=100.0,
                                       algorithm=algorithm))
    # after correction panel centers are ~0, not ~±offset
    est = np.median(corrected, axis=(-2, -1)) if algorithm == "median" else corrected.mean((-2, -1))
    np.testing.assert_allclose(est, 0.0, atol=0.15)


def test_common_mode_ignores_signal_pixels():
    # bright peaks above threshold must not drag the baseline
    x = np.zeros((1, 16, 128), np.float32) + 2.0
    x[0, 8, :64] = 1000.0  # signal
    out = np.asarray(common_mode(jnp.asarray(x), threshold=10.0, algorithm="mean"))
    np.testing.assert_allclose(out[0, 0, 0], 0.0, atol=1e-5)  # 2.0 baseline removed


def test_common_mode_respects_mask():
    x = np.zeros((1, 16, 128), np.float32)
    x[0, :8] = 4.0  # top half is "hot" but masked off
    mask = np.ones((1, 16, 128), np.uint8)
    mask[0, :8] = 0
    out = np.asarray(common_mode(jnp.asarray(x), mask=jnp.asarray(mask), threshold=100.0))
    np.testing.assert_allclose(out[0, 8:], 0.0, atol=1e-6)


def test_calibrate_recovers_photons(frame_set):
    # raw = ped + adu_gain * photons * gain + cm + noise; calibrate should
    # recover ~adu_gain*photons (we don't divide by adu_gain — that's the
    # detector gain map, not the photon conversion)
    fs = frame_set
    out = np.asarray(
        calibrate(
            jnp.asarray(fs["raw"]),
            jnp.asarray(fs["pedestal"]),
            jnp.asarray(fs["gain"]),
            jnp.asarray(fs["mask"]),
            cm_threshold=20.0,
        )
    )
    calib_truth = np.stack(
        [fs["src"].event(i, RetrievalMode.CALIB)[0] for i in range(3)]
    ) * fs["src"].spec.adu_gain
    good = fs["mask"].astype(bool)
    # background pixels should sit near 0; peak pixels near the truth
    err = np.abs(out - calib_truth)[..., good]
    assert np.median(err) < 2.0  # noise floor ~2.5 ADU rms
    # masked pixels exactly zero
    assert np.all(out[..., ~good] == 0)


def test_fused_matches_xla_path(frame_set):
    fs = frame_set
    args = (
        jnp.asarray(fs["raw"]),
        jnp.asarray(fs["pedestal"]),
        jnp.asarray(fs["gain"]),
        jnp.asarray(fs["mask"]),
    )
    ref = np.asarray(calibrate(*args, cm_threshold=10.0, cm_algorithm="mean"))
    fused = np.asarray(fused_calibrate(*args, threshold=10.0))
    np.testing.assert_allclose(fused, ref, rtol=1e-5, atol=1e-4)


def test_fused_integer_raw_promotes(frame_set):
    # uint16 ADUs (typical detector readout) must promote to float, not
    # demote the calibration constants to integers
    fs = frame_set
    raw_u16 = np.clip(fs["raw"], 0, 65535).astype(np.uint16)
    args = (
        jnp.asarray(fs["pedestal"]),
        jnp.asarray(fs["gain"]),
        jnp.asarray(fs["mask"]),
    )
    fused = np.asarray(fused_calibrate(jnp.asarray(raw_u16), *args, threshold=10.0))
    ref = np.asarray(
        calibrate(jnp.asarray(raw_u16.astype(np.float32)), *args, cm_threshold=10.0)
    )
    assert fused.dtype == np.float32
    np.testing.assert_allclose(fused, ref, rtol=1e-5, atol=1e-4)


def test_fused_unbatched(frame_set):
    fs = frame_set
    out = fused_calibrate(
        jnp.asarray(fs["raw"][0]),
        jnp.asarray(fs["pedestal"]),
        jnp.asarray(fs["gain"]),
        jnp.asarray(fs["mask"]),
    )
    assert out.shape == fs["raw"][0].shape


class TestCalibOutDtype:
    def test_bf16_output_matches_f32_to_tolerance(self, rng):
        import jax.numpy as jnp
        import numpy as np

        from psana_ray_tpu.ops import fused_calibrate

        p, h, w = 2, 64, 128
        ped = rng.normal(1000, 5, size=(p, h, w)).astype(np.float32)
        gain = (1 + 0.02 * rng.normal(size=(p, h, w))).astype(np.float32)
        mask = (rng.random((p, h, w)) > 0.05).astype(np.uint8)
        raw = (ped + 30 * rng.normal(size=(4, p, h, w))).astype(np.float32)
        f32 = fused_calibrate(raw, ped, gain, mask, threshold=10.0)
        b16 = fused_calibrate(raw, ped, gain, mask, threshold=10.0, out_dtype=jnp.bfloat16)
        assert b16.dtype == jnp.bfloat16
        scale = float(np.max(np.abs(np.asarray(f32)))) + 1e-6
        err = np.max(np.abs(np.asarray(f32) - np.asarray(b16, np.float32))) / scale
        assert err < 0.01  # bf16 rounding of the final store only
