"""Replicated partition logs + coordinator leases (ISSUE 11).

Layers, fast to slow:

- follower placement is the rendezvous ranking (the chain property:
  the failover target of a dead owner IS its replica holder);
- SegmentLog reconciliation primitives (append_at / truncate_to /
  reset_to) survive reopen;
- ReplicaSet ingest semantics: overlap truncates, gaps reset, floors
  commit, promotion fences;
- two-server end-to-end: the replicated ack floor (a producer ack
  means the follower logged it), loud degrade when the follower link
  is down, owner death -> promote -> the follower serves the backlog
  and the replay range — including after the owner's DISK is deleted;
- cluster failover with groups: kill the coordinator AND delete its
  durable dir mid-run; lost == 0, the group's generation/drained
  state survives on the failed-over coordinator (stale-generation
  commits still fenced), replay still serves the retained range;
- the full-jitter reconnect backoff spread (ISSUE 11 satellite);
- a failing durable disk degrades loudly ('E' + breadcrumb), never
  kills the event loop (ISSUE 11 satellite, DiskFaultInjector);
- slow: a 3-server chaos loop (kill-and-restart a random server under
  open-loop load, once deleting its disk) with zero loss.
"""

from __future__ import annotations

import os
import shutil
import socket
import threading
import time

import numpy as np
import pytest

from psana_ray_tpu.cluster.hashring import (
    PartitionMap,
    next_in_chain,
    partition_follower,
    partition_owner,
    ranked_owners,
)
from psana_ray_tpu.cluster.replication import (
    ReplicaSet,
    ReplicationManager,
    parse_partition,
)
from psana_ray_tpu.obs.flight import FLIGHT
from psana_ray_tpu.records import EndOfStream, FrameRecord, is_eos
from psana_ray_tpu.storage import DurableRingBuffer, SegmentLog
from psana_ray_tpu.transport.registry import TransportClosed
from psana_ray_tpu.transport.tcp import (
    _REPL_NO_FLOOR,
    TcpQueueClient,
    TcpQueueServer,
)

from faultproxy import DiskFaultInjector


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _frame(i: int, shape=(2, 8, 8)) -> FrameRecord:
    panels = np.full(shape, i % 4096, dtype=np.uint16)
    return FrameRecord(0, i, panels, 1.0)


def _pick_queue_name(peers, owner: str, prefix: str = "rq") -> str:
    """A queue name whose rank-0 owner (partition 0) is ``owner`` —
    keeps the two-server tests deterministic about who ships where."""
    for i in range(512):
        name = f"{prefix}_{i}"
        if partition_owner(peers, name, 0) == owner:
            return name
    raise AssertionError("no suitable queue name in 512 tries")


def _durable_factory(durable_dir, fsync="none", segment_bytes=1 << 20):
    def factory(ns, name, maxsize):
        qdir = os.path.join(durable_dir, f"{ns}__{name}")
        log = SegmentLog(
            qdir, segment_bytes=segment_bytes, fsync=fsync,
            name=f"{ns}/{name}",
        )
        return DurableRingBuffer(log, maxsize=maxsize, name=f"{ns}__{name}")

    return factory


def _replicated_server(durable_dir, peers, advertise, port,
                       group_store=False, **mgr_kw):
    mgr = ReplicationManager(durable_dir, peers, advertise, **mgr_kw)
    srv = TcpQueueServer(
        host="127.0.0.1", port=port, maxsize=256,
        queue_factory=_durable_factory(durable_dir),
        replication=mgr,
        group_store_path=(
            os.path.join(durable_dir, "groups.json") if group_store else None
        ),
    )
    return srv.serve_background()


# ---------------------------------------------------------------------------
# follower placement: the chain IS the rendezvous ranking
# ---------------------------------------------------------------------------
class TestFollowerPlacement:
    PEERS = ["h1:1", "h2:2", "h3:3", "h4:4"]

    def test_ranking_is_deterministic_and_total(self):
        for p in range(8):
            ranked = ranked_owners(self.PEERS, "q", p)
            assert sorted(ranked) == sorted(self.PEERS)
            assert ranked == ranked_owners(list(reversed(self.PEERS)), "q", p)
            assert ranked[0] == partition_owner(self.PEERS, "q", p)
            assert ranked[1] == partition_follower(self.PEERS, "q", p)

    def test_follower_is_the_failover_target(self):
        """The property the whole design leans on: when the owner dies,
        the recomputed map hands the partition to the server already
        holding its replica."""
        m = PartitionMap.compute(self.PEERS, "q", 8)
        for p in range(8):
            owner = m.assignments[p]
            follower = m.follower_of(p)
            assert follower is not None and follower != owner
            survivors = [s for s in self.PEERS if s != owner]
            assert partition_owner(survivors, "q", p) == follower

    def test_next_in_chain_walks_the_ranking(self):
        ranked = ranked_owners(self.PEERS, "q", 3)
        for i, server in enumerate(ranked):
            nxt = next_in_chain(self.PEERS, server, "q", 3)
            if i + 1 < len(ranked):
                assert nxt == ranked[i + 1]
            else:
                assert nxt is None
        assert next_in_chain(self.PEERS, "h9:9", "q", 3) is None

    def test_single_server_has_no_follower(self):
        assert partition_follower(["h1:1"], "q", 0) is None

    def test_parse_partition(self):
        assert parse_partition("shared_queue#p3") == ("shared_queue", 3)
        assert parse_partition("plain") == ("plain", 0)
        assert parse_partition("odd#px") == ("odd#px", 0)


# ---------------------------------------------------------------------------
# SegmentLog reconciliation primitives
# ---------------------------------------------------------------------------
class TestLogReconciliation:
    def _log(self, tmp_path, name="l", **kw):
        kw.setdefault("segment_bytes", 4096)
        kw.setdefault("fsync", "none")
        return SegmentLog(str(tmp_path / name), **kw)

    def test_truncate_to_mid_segment_and_reappend(self, tmp_path):
        log = self._log(tmp_path)
        for i in range(10):
            log.append({"i": i})
        log.truncate_to(6)
        assert log.next_offset == 6
        assert log.read(5) == {"i": 5}
        with pytest.raises(KeyError):
            log.read(6)
        # the tail is clean: appends continue exactly at the cut
        assert log.append({"i": "new6"}) == 6
        log.close()
        # ...and a recovery scan agrees (no torn tail from the scrub)
        log2 = self._log(tmp_path)
        assert log2.next_offset == 7
        assert log2.read(6) == {"i": "new6"}
        assert not log2.torn_tail_repaired
        log2.close()

    def test_truncate_across_segments(self, tmp_path):
        log = self._log(tmp_path, segment_bytes=512)
        payload = {"pad": "x" * 100}
        for i in range(12):
            log.append(dict(payload, i=i))
        assert len(log.stats()["committed"]) == 0
        assert log.stats()["segments"] > 1
        log.truncate_to(3)
        assert log.next_offset == 3
        assert log.read(2)["i"] == 2
        for i in range(3, 6):
            assert log.append(dict(payload, i=i)) == i
        log.close()
        log2 = self._log(tmp_path, segment_bytes=512)
        assert log2.next_offset == 6
        assert [log2.read(i)["i"] for i in range(6)] == list(range(6))
        log2.close()

    def test_reset_to_starts_a_new_offset_space(self, tmp_path):
        log = self._log(tmp_path)
        for i in range(5):
            log.append({"i": i})
        log.reset_to(100)
        assert log.next_offset == 100
        assert log.first_retained_offset() == 100
        assert log.append_at(100, {"i": 100}) == 100
        log.close()
        log2 = self._log(tmp_path)
        assert log2.next_offset == 101
        assert log2.read(100) == {"i": 100}
        log2.close()

    def test_append_at_enforces_contiguity(self, tmp_path):
        log = self._log(tmp_path)
        log.append_at(0, {"i": 0})
        with pytest.raises(ValueError, match="out of order"):
            log.append_at(5, {"i": 5})
        with pytest.raises(ValueError, match="out of order"):
            log.append_at(0, {"i": 0})
        log.close()


# ---------------------------------------------------------------------------
# ReplicaSet ingest semantics
# ---------------------------------------------------------------------------
class TestReplicaSetIngest:
    def test_ingest_overlap_truncates_and_gap_resets(self, tmp_path):
        rs = ReplicaSet(str(tmp_path), segment_bytes=1 << 16, fsync="none")
        entry = rs.subscribe_log("ns", "q")
        assert entry is not None
        for i in range(6):
            assert rs.ingest(entry, i, _REPL_NO_FLOOR, {"i": i})
        # overlap: the owner's view of the suffix wins
        assert rs.ingest(entry, 4, _REPL_NO_FLOOR, {"i": "re4"})
        assert entry.log.next_offset == 5
        assert entry.log.read(4) == {"i": "re4"}
        # forward gap: retention passed us -> reset, loudly
        assert rs.ingest(entry, 50, _REPL_NO_FLOOR, {"i": 50})
        assert entry.log.first_retained_offset() == 50
        assert entry.log.next_offset == 51
        rs.close_all()

    def test_floor_commits_ride_with_stride_and_promote_is_exact(self, tmp_path):
        rs = ReplicaSet(str(tmp_path), segment_bytes=1 << 16, fsync="none")
        entry = rs.subscribe_log("ns", "q")
        for i in range(8):
            rs.ingest(entry, i, floor=i - 2, item={"i": i})
        # stride (32) not reached: nothing committed yet
        assert entry.log.committed("") == -1
        rng = rs.promote("ns", "q")
        assert rng == (0, 8)
        # promotion committed the exact latest piggybacked floor
        reopened = SegmentLog(str(tmp_path / "ns__q"), fsync="none")
        assert reopened.committed("") == 5
        reopened.close()

    def test_promotion_fences_ingest_and_resubscribe(self, tmp_path):
        rs = ReplicaSet(str(tmp_path), segment_bytes=1 << 16, fsync="none")
        entry = rs.subscribe_log("ns", "q")
        assert rs.ingest(entry, 0, _REPL_NO_FLOOR, {"i": 0})
        assert rs.promote("ns", "q") is not None
        assert rs.promote("ns", "q") is None  # second promote: nothing left
        assert not rs.ingest(entry, 1, _REPL_NO_FLOOR, {"i": 1})  # fenced
        assert rs.subscribe_log("ns", "q") is None  # zombie resubscribe


# ---------------------------------------------------------------------------
# two-server end-to-end: ack floor, degrade, promote
# ---------------------------------------------------------------------------
class TestReplicationEndToEnd:
    def _pair(self, tmp_path, **mgr_kw):
        dirs = [str(tmp_path / f"s{i}") for i in range(2)]
        for d in dirs:
            os.makedirs(d, exist_ok=True)
        ports = [_free_port(), _free_port()]
        peers = [f"127.0.0.1:{p}" for p in ports]
        servers = [
            _replicated_server(dirs[i], peers, peers[i], ports[i], **mgr_kw)
            for i in range(2)
        ]
        return dirs, ports, peers, servers

    def test_flush_means_follower_logged_and_promote_serves(self, tmp_path):
        dirs, ports, peers, servers = self._pair(tmp_path)
        try:
            qname = _pick_queue_name(peers, peers[0])
            c = TcpQueueClient(
                "127.0.0.1", ports[0], namespace="ns", queue_name=qname
            )
            n = 24
            for i in range(n):
                assert c.put_pipelined(
                    _frame(i), deadline=time.monotonic() + 30
                )
            assert c.flush_puts(time.monotonic() + 30)
            # consume-and-ack a few on the owner: the committed floor
            # piggybacks onto later appends/promote
            got = c.get_batch(4, timeout=5.0)
            assert len(got) == 4
            c.disconnect()
            # flush returned: every frame is follower-acked — its
            # replica log holds ALL of them (the replicated ack floor)
            servers[1].shutdown()  # releases the replica mmap
            rlog = SegmentLog(
                os.path.join(dirs[1], f"ns__{qname}"), fsync="none"
            )
            assert rlog.next_offset == n
            assert not rlog.torn_tail_repaired
            rlog.close()
        finally:
            for s in servers:
                s.shutdown()

    def test_owner_death_promote_serves_backlog_and_replay(self, tmp_path):
        dirs, ports, peers, servers = self._pair(tmp_path)
        try:
            qname = _pick_queue_name(peers, peers[0])
            c = TcpQueueClient(
                "127.0.0.1", ports[0], namespace="ns", queue_name=qname
            )
            n = 16
            for i in range(n):
                assert c.put_pipelined(
                    _frame(i), deadline=time.monotonic() + 30
                )
            assert c.flush_puts(time.monotonic() + 30)
            c.disconnect()
            # kill the owner AND delete its disk: the bytes now exist
            # ONLY on the follower
            servers[0].shutdown()
            shutil.rmtree(dirs[0])
            c2 = TcpQueueClient("127.0.0.1", ports[1])
            rng = c2.promote("ns", qname)
            assert rng is not None and rng["end"] == n
            c2.open("ns", qname, 256)
            drained = []
            while True:
                batch = c2.get_batch(64, timeout=2.0)
                if not batch:
                    break
                drained.extend(batch)
            assert sorted(r.event_idx for r in drained) == list(range(n))
            c2.disconnect()
            # the promoted queue still serves the retained range as a
            # non-destructive replay
            c3 = TcpQueueClient(
                "127.0.0.1", ports[1], namespace="ns", queue_name=qname
            )
            rng2 = c3.replay_open(from_offset="begin", group="audit")
            assert rng2["end"] - rng2["start"] == n
            replayed = []
            while True:
                batch = c3.get_batch(64, timeout=1.0)
                if not batch:
                    break
                replayed.extend(batch)
            assert len(replayed) == n
            c3.disconnect()
        finally:
            for s in servers:
                s.shutdown()

    def test_owner_restarted_behind_replica_is_fenced_not_rewound(
        self, tmp_path
    ):
        """A server that comes back with an emptied disk while its
        replica holds acked records must NOT rewind the replica to
        mirror its empty log (that would destroy the only surviving
        copy): the owner fences itself, loudly, and serves degraded."""
        dirs, ports, peers, servers = self._pair(
            tmp_path, degrade_after_s=0.5
        )
        try:
            qname = _pick_queue_name(peers, peers[0])
            c = TcpQueueClient(
                "127.0.0.1", ports[0], namespace="ns", queue_name=qname
            )
            n = 12
            for i in range(n):
                assert c.put_pipelined(
                    _frame(i), deadline=time.monotonic() + 30
                )
            assert c.flush_puts(time.monotonic() + 30)
            c.disconnect()
            # the machine "loses its disk" but comes back FAST — before
            # any client wrote it off
            servers[0].shutdown()
            shutil.rmtree(dirs[0])
            os.makedirs(dirs[0])
            servers[0] = _replicated_server(
                dirs[0], peers, peers[0], ports[0], degrade_after_s=0.5
            )
            fenced_before = FLIGHT.count_of("replication_fenced")
            c2 = TcpQueueClient(
                "127.0.0.1", ports[0], namespace="ns", queue_name=qname
            )
            # the restarted owner serves (degraded once fenced) ...
            assert c2.put_wait(_frame(99), timeout=15.0)
            deadline = time.monotonic() + 10
            while (
                FLIGHT.count_of("replication_fenced") == fenced_before
                and time.monotonic() < deadline
            ):
                time.sleep(0.05)
            assert FLIGHT.count_of("replication_fenced") > fenced_before
            c2.disconnect()
            # ... and the follower's replica survived untouched
            servers[1].shutdown()
            rlog = SegmentLog(
                os.path.join(dirs[1], f"ns__{qname}"), fsync="none"
            )
            assert rlog.next_offset == n
            rlog.close()
        finally:
            for s in servers:
                if s is not None:
                    s.shutdown()

    def test_acks_held_until_follower_logs(self, tmp_path):
        """The replicated ack floor, pinned directly: with the follower
        ABSENT and a long degrade grace, windowed puts stay
        unacknowledged; once the grace lapses the owner degrades loudly
        and acks flow."""
        d = str(tmp_path / "owner")
        os.makedirs(d)
        port = _free_port()
        dead_port = _free_port()  # nothing ever listens here
        peers = [f"127.0.0.1:{port}", f"127.0.0.1:{dead_port}"]
        srv = _replicated_server(
            d, peers, peers[0], port, degrade_after_s=1.0
        )
        try:
            qname = _pick_queue_name(peers, peers[0])
            c = TcpQueueClient(
                "127.0.0.1", port, namespace="ns", queue_name=qname,
                put_window=4,
            )
            t0 = time.monotonic()
            assert c.put_pipelined(_frame(0), deadline=t0 + 30)
            # held: the follower never acked, and the grace has not
            # lapsed — a short flush deadline must expire
            assert not c.flush_puts(time.monotonic() + 0.3)
            # ...then the degrade opens the gate, loudly
            assert c.flush_puts(time.monotonic() + 10.0)
            assert time.monotonic() - t0 >= 0.9
            assert FLIGHT.count_of("replication_degraded") >= 1
            c.disconnect()
        finally:
            srv.shutdown()


    def test_hung_follower_degrades_after_grace(self, tmp_path):
        """A follower that ACCEPTS the connection but stops acking
        (hung peer / blackholed link after the window filled) must hit
        the same degrade grace as a refused dial — producers never
        wedge behind a connected-but-silent follower."""
        from faultproxy import FaultProxy

        d0, d1 = str(tmp_path / "o"), str(tmp_path / "f")
        os.makedirs(d0)
        os.makedirs(d1)
        oport, fport = _free_port(), _free_port()
        proxy = FaultProxy("127.0.0.1", fport)
        peers = [f"127.0.0.1:{oport}", f"127.0.0.1:{proxy.port}"]
        owner = _replicated_server(
            d0, peers, peers[0], oport, degrade_after_s=1.0
        )
        follower = _replicated_server(d1, peers, peers[1], fport)
        try:
            qname = _pick_queue_name(peers, peers[0])
            # let the subscribe exchange through, then stall the
            # owner->follower direction mid-first-append, forever
            proxy.stall_at("up", 256, stall_s=120.0)
            degr0 = FLIGHT.count_of("replication_degraded")
            c = TcpQueueClient(
                "127.0.0.1", oport, namespace="ns", queue_name=qname
            )
            for i in range(6):
                assert c.put_pipelined(
                    _frame(i), deadline=time.monotonic() + 30
                )
            assert c.flush_puts(time.monotonic() + 20)
            assert FLIGHT.count_of("replication_degraded") > degr0
            c.disconnect()
        finally:
            owner.shutdown()
            follower.shutdown()
            proxy.close()


def test_unknown_replica_codec_fails_fast(tmp_path):
    """An unknown --replica_codec must die at manager construction —
    raising inside the shipper thread instead would kill it silently
    and leave the replicated ack floor gating producers forever."""
    with pytest.raises(ValueError):
        ReplicationManager(
            str(tmp_path), ["a:1", "b:2"], "a:1", codec="no-such-codec"
        )


# ---------------------------------------------------------------------------
# cluster failover: kill the coordinator AND delete its disk
# ---------------------------------------------------------------------------
class TestClusterFailover:
    def test_kill_coordinator_and_delete_disk_loses_nothing(self, tmp_path):
        from psana_ray_tpu.cluster.client import ClusterClient

        N, P, NF = 3, 4, 60
        dirs = [str(tmp_path / f"s{i}") for i in range(N)]
        for d in dirs:
            os.makedirs(d)
        ports = [_free_port() for _ in range(N)]
        peers = [f"127.0.0.1:{p}" for p in ports]
        servers = [
            _replicated_server(
                dirs[i], peers, peers[i], ports[i], group_store=True
            )
            for i in range(N)
        ]
        prod = cons = None
        try:
            prod = ClusterClient(
                peers, queue_name="cq", n_partitions=P, maxsize=256,
                retain=256, reconnect_tries=1, reconnect_base_s=0.05,
            )
            cons = ClusterClient(
                peers, queue_name="cq", n_partitions=P, maxsize=256,
                group="g1", reconnect_tries=1, reconnect_base_s=0.05,
            )
            err = {}

            def produce():
                try:
                    for i in range(NF):
                        assert prod.put_pipelined(
                            _frame(i), deadline=time.monotonic() + 60
                        ), i
                        if i == NF // 3:
                            # the acceptance move: kill the COORDINATOR
                            # (server 0) and delete its durable dir —
                            # its partitions AND the group state must
                            # both survive
                            servers[0].shutdown()
                            shutil.rmtree(dirs[0])
                    assert prod.flush_puts(time.monotonic() + 60)
                    assert prod.put_wait(
                        EndOfStream(0, -1, 1, 1), timeout=60
                    )
                except BaseException as e:  # noqa: BLE001 — reported below
                    err["e"] = e

            t = threading.Thread(target=produce, daemon=True)
            t.start()
            seen, eos = [], 0
            deadline = time.monotonic() + 120
            while not eos and time.monotonic() < deadline:
                if "e" in err:
                    raise err["e"]
                for item in cons.get_batch_stream(32, timeout=0.5):
                    if is_eos(item):
                        eos += 1
                    else:
                        seen.append(item.event_idx)
            t.join(10)
            if "e" in err:
                raise err["e"]
            assert eos == 1, "group EOS never fired after the failover"
            lost = sorted(set(range(NF)) - set(seen))
            assert not lost, f"LOST {len(lost)}: {lost[:10]}"
            # the coordinator's group state survived the failover:
            # generation continued and a stale-generation commit from a
            # zombie member is FENCED, not applied
            info = cons._rpc({"op": "info", "group": "g1"})
            assert info["ok"] and len(info["drained"]) == P
            stale = cons._rpc({
                "op": "drained", "group": "g1", "member": "zombie",
                "generation": info["generation"] - 1, "partition": 0,
            })
            assert stale.get("fenced"), stale
            # replay still serves a retained range from the promoted
            # partitions (partition logs survived the deleted disk)
            replayer = ClusterClient(
                [a for a in peers if a != peers[0]],
                queue_name="cq", n_partitions=P, maxsize=256,
                reconnect_tries=1, reconnect_base_s=0.05,
            )
            try:
                replayer.replay_open(from_offset="begin", group="audit")
                replayed = []
                empty_reads = 0
                while empty_reads < 3:
                    batch = replayer.get_batch(64, timeout=1.0)
                    if batch:
                        replayed.extend(
                            b for b in batch if not is_eos(b)
                        )
                        empty_reads = 0
                    else:
                        empty_reads += 1
                assert len({r.event_idx for r in replayed}) >= NF // 2
            finally:
                replayer.disconnect()
        finally:
            for c in (prod, cons):
                if c is not None:
                    try:
                        c.disconnect()
                    except Exception:
                        pass
            for s in servers:
                try:
                    s.shutdown()
                except Exception:
                    pass


# ---------------------------------------------------------------------------
# satellite: full-jitter reconnect backoff
# ---------------------------------------------------------------------------
class TestReconnectJitter:
    def test_backoff_sleeps_are_jittered_not_lockstep(self, monkeypatch):
        """Every backoff sleep draws uniform from [0, envelope) — three
        clients that watched the same server die must NOT redial in
        lockstep (the thundering herd that would land on a freshly
        promoted follower)."""
        sleeps = []
        monkeypatch.setattr(time, "sleep", lambda s: sleeps.append(s))
        port = _free_port()  # nothing listens: every dial fails fast
        per_client = []
        for _ in range(3):
            before = len(sleeps)
            with pytest.raises(TransportClosed):
                TcpQueueClient(
                    "127.0.0.1", port, timeout_s=0.2,
                    reconnect_tries=5, reconnect_base_s=0.05,
                )
            per_client.append(sleeps[before:])
        caps = [0.05, 0.1, 0.2, 0.4]  # envelope per between-dial pause
        for client_sleeps in per_client:
            assert len(client_sleeps) == len(caps)
            for s, cap in zip(client_sleeps, caps):
                assert 0.0 <= s < cap  # strict: uniform never hits the cap
        # spread across clients: the first pause differs client-to-client
        firsts = [cs[0] for cs in per_client]
        assert len(set(firsts)) == len(firsts), firsts


# ---------------------------------------------------------------------------
# satellite: a failing durable disk degrades loudly, never kills the loop
# ---------------------------------------------------------------------------
class TestDiskFaultDegradesLoudly:
    def test_enospc_answers_E_and_loop_survives(self, tmp_path):
        srv = TcpQueueServer(
            host="127.0.0.1", port=0, maxsize=64,
            queue_factory=_durable_factory(str(tmp_path)),
        ).serve_background()
        try:
            c = TcpQueueClient(
                "127.0.0.1", srv.port, namespace="ns", queue_name="dq",
            )
            assert c.put(_frame(0))  # healthy disk baseline
            faults_before = FLIGHT.count_of("disk_fault")
            with DiskFaultInjector() as inj:
                # the full disk is a protocol ANSWER ('E'), not a
                # connection death, and not a loop death
                with pytest.raises(RuntimeError, match="protocol error"):
                    c.put(_frame(1))
                assert inj.fired >= 1
                assert FLIGHT.count_of("disk_fault") > faults_before
                # the loop is alive mid-fault: reads still serve
                assert c.size() >= 1
            # disk recovered: puts flow again and everything drains
            assert c.put(_frame(2))
            got = c.get_batch(16, timeout=5.0)
            assert sorted(r.event_idx for r in got) == [0, 2]
            c.disconnect()
        finally:
            srv.shutdown()

    def test_injector_arms_after_n_ok_ops(self, tmp_path):
        log = SegmentLog(str(tmp_path / "l"), fsync="none")
        with DiskFaultInjector(ok_ops=2, ops=("append",)):
            log.append({"i": 0})
            log.append({"i": 1})
            with pytest.raises(OSError):
                log.append({"i": 2})
        assert log.append({"i": 3}) == 2  # offset 2 was never consumed
        log.close()


# ---------------------------------------------------------------------------
# slow: chaos — kill-and-restart a random server under open-loop load
# ---------------------------------------------------------------------------
@pytest.mark.slow
class TestChaosKillRestart:
    def test_three_server_chaos_loses_nothing(self, tmp_path):
        """Repeated kill-and-restart under open-loop load, one victim
        losing its DISK. Two distinct failure shapes, matching the
        documented failover contract:

        - an intact-disk victim restarts FAST: clients ride the
          reconnect envelope (no death verdict), the recovered log
          re-exposes, the windowed resend covers the gap;
        - the deleted-disk victim is a dead MACHINE: it stays down
          until both clients have written it off (per-client-permanent
          verdict) and its partitions serve from promoted replicas.
          A fast restart with an emptied disk would instead be fenced
          by the owner-behind-replica refusal — pinned separately.
        """
        import random as _random

        from psana_ray_tpu.cluster.client import ClusterClient

        rng = _random.Random(1311)
        N, P, NF = 3, 4, 240
        dirs = [str(tmp_path / f"s{i}") for i in range(N)]
        for d in dirs:
            os.makedirs(d)
        ports = [_free_port() for _ in range(N)]
        peers = [f"127.0.0.1:{p}" for p in ports]

        def boot(i):
            return _replicated_server(
                dirs[i], peers, peers[i], ports[i], group_store=True
            )

        servers = [boot(i) for i in range(N)]
        prod = cons = None
        try:
            prod = ClusterClient(
                peers, queue_name="chaos", n_partitions=P, maxsize=256,
                retain=512, reconnect_tries=6, reconnect_base_s=0.1,
            )
            cons = ClusterClient(
                peers, queue_name="chaos", n_partitions=P, maxsize=256,
                reconnect_tries=6, reconnect_base_s=0.1,
            )
            err = {}
            kills = {"n": 0, "deleted": False}
            dead_idx = []

            def restart_victim():
                candidates = [j for j in range(N) if j not in dead_idx]
                victim = rng.choice(candidates)
                servers[victim].shutdown()
                servers[victim] = boot(victim)  # intact disk: clients
                kills["n"] += 1                 # ride the reconnect

            def delete_victim():
                # only a victim that OWNS partitions exercises anything
                owners = {
                    prod.partition_map.assignments[p] for p in range(P)
                }
                candidates = [
                    j for j in range(N)
                    if j not in dead_idx and peers[j] in owners
                ]
                victim = rng.choice(candidates)
                servers[victim].shutdown()
                shutil.rmtree(dirs[victim])
                servers[victim] = None  # the machine is gone for good
                dead_idx.append(victim)
                kills["n"] += 1
                kills["deleted"] = True
                # no wait needed: the server never returns, so BOTH
                # clients inevitably write it off on their next op
                # against its partitions (the producer's very next
                # round-robin put, the consumer's next sweep) — and a
                # post-run assert pins that they did

            plan = {
                NF // 5: restart_victim,
                2 * NF // 5: delete_victim,
                3 * NF // 5: restart_victim,
            }

            def produce():
                try:
                    for i in range(NF):
                        assert prod.put_pipelined(
                            _frame(i), deadline=time.monotonic() + 120
                        ), i
                        action = plan.get(i)
                        if action is not None:
                            action()
                    assert prod.flush_puts(time.monotonic() + 120)
                    assert prod.put_wait(
                        EndOfStream(0, -1, 1, 1), timeout=120
                    )
                except BaseException as e:  # noqa: BLE001 — reported below
                    err["e"] = e

            t = threading.Thread(target=produce, daemon=True)
            t.start()
            seen, eos = [], 0
            deadline = time.monotonic() + 300
            while not eos and time.monotonic() < deadline:
                if "e" in err:
                    raise err["e"]
                for item in cons.get_batch_stream(32, timeout=0.5):
                    if is_eos(item):
                        eos += 1
                    else:
                        seen.append(item.event_idx)
            t.join(15)
            if "e" in err:
                raise err["e"]
            assert eos == 1, "end-of-stream never fired"
            assert kills["n"] >= 3 and kills["deleted"]
            lost = sorted(set(range(NF)) - set(seen))
            assert not lost, f"chaos LOST {len(lost)}: {lost[:10]}"
            # both clients wrote the dead machine off (no split-brain)
            gone = peers[dead_idx[0]]
            assert gone not in prod.partition_map.servers
            assert gone not in cons.partition_map.servers
            # replay still serves the retained range after the chaos
            live = [
                a for i, a in enumerate(peers)
                if servers[i] is not None
            ]
            replayer = ClusterClient(
                live, queue_name="chaos", n_partitions=P, maxsize=256,
                reconnect_tries=2, reconnect_base_s=0.1,
            )
            try:
                replayer.replay_open(from_offset="begin", group="audit")
                replayed = set()
                empty_reads = 0
                while empty_reads < 3:
                    batch = replayer.get_batch(64, timeout=1.0)
                    if batch:
                        replayed |= {
                            b.event_idx for b in batch if not is_eos(b)
                        }
                        empty_reads = 0
                    else:
                        empty_reads += 1
                assert len(replayed) >= NF // 2
            finally:
                replayer.disconnect()
        finally:
            for c in (prod, cons):
                if c is not None:
                    try:
                        c.disconnect()
                    except Exception:
                        pass
            for s in servers:
                try:
                    s.shutdown()
                except Exception:
                    pass
