"""bench.py artifact + watchdog machinery regression tests.

Two real failures drove these defenses and must never come back:

- Round 4's driver artifact was unparseable (``BENCH_r04.json:
  parsed=null``) because the final JSON line outgrew the driver's tail
  window — the compact final line is now hard-capped and self-checked.
- Two round-5 full-bench runs were forfeited by one-stage section
  watchdogs ``os._exit``-ing on transient multi-minute tunnel stalls —
  a section overrun now soft-cancels (async ``SectionTimeout`` into the
  main thread) so later sections still run, with the hard exit reserved
  for stalls that outlive the grace period.

These tests run the REAL machinery (real Watchdog thread, real
``run_section``) on fake sections; no jax/TPU involved. ``bench_full.json``
writes land in the repo root but the file is gitignored and regenerated
by every bench run.
"""

import json
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


@pytest.fixture
def fresh_final():
    """Snapshot/restore the module-global artifact dict around each test."""
    snap = dict(bench._FINAL)
    yield bench._FINAL
    bench._FINAL.clear()
    bench._FINAL.update(snap)


def test_compact_line_parseable_and_capped_under_adversarial_growth(fresh_final):
    """The r4 regression: no matter how large the extras dict grows, the
    final emitted line must parse and stay under the hard cap."""
    f = bench._FINAL
    f["value"] = 28000.5
    f["vs_baseline"] = 44.8
    for i in range(500):  # ~50 KB of junk keys — far past the cap
        f[f"device_bulk_diag_{i}"] = [round(i * 0.1, 3)] * 40
    line = bench._compact_line()
    d = json.loads(line)  # must parse
    assert len(line) <= bench._COMPACT_CAP + 1  # +1: trailing newline
    # the four headline fields always survive
    assert d["metric"] == bench._FINAL["metric"]
    assert d["value"] == 28000.5
    assert d["unit"] == "frames/s"
    assert d["vs_baseline"] == 44.8


def test_compact_line_prefers_judged_keys_over_bulk(fresh_final):
    f = bench._FINAL
    for i in range(500):
        f[f"device_bulk_diag_{i}"] = [i] * 40
    # priority keys added AFTER the junk must still make the line
    f["device_resnet50_accuracy"] = 1.0
    f["device_unet_recall"] = 0.99
    d = json.loads(bench._compact_line())
    assert d["device_resnet50_accuracy"] == 1.0
    assert d["device_unet_recall"] == 0.99
    assert not any(k.startswith("device_bulk_diag_") for k in d)


def test_stalled_section_soft_cancels_and_later_sections_run(fresh_final):
    """The r5 tunnel-stall scenario: a section blocked past its budget in
    resumable work is cancelled in place; the sections after it run and
    the cancel is recorded in the artifact."""
    wd = bench.Watchdog()
    hit = {}

    def stalls():
        for _ in range(600):  # a 60 s "stall" in interruptible slices
            time.sleep(0.1)
        raise AssertionError("watchdog never cancelled the stall")

    def later():
        hit["later"] = True

    t0 = time.monotonic()
    assert bench.run_section(wd, "fake-stall", stalls, budget_s=1.5) is False
    assert time.monotonic() - t0 < 30.0  # cancelled, not run to completion
    assert bench.run_section(wd, "fake-later", later, budget_s=30.0) is False
    assert hit.get("later") is True
    assert "fake-stall" in bench._FINAL["sections_soft_cancelled"]
    assert "fake-later" not in bench._FINAL.get("sections_soft_cancelled", "")


def test_near_deadline_completion_does_not_poison_next_section(fresh_final):
    """A section finishing right around its deadline must not leave a
    stale cancel that aborts the (healthy, in-budget) next section."""
    wd = bench.Watchdog()
    ran = {}

    def near_deadline():
        time.sleep(1.4)  # budget 1.5 s, watchdog polls every 0.5 s

    def healthy():
        ran["healthy"] = True

    bench.run_section(wd, "fake-near", near_deadline, budget_s=1.5)
    bench.run_section(wd, "fake-healthy", healthy, budget_s=30.0)
    assert ran.get("healthy") is True
    assert "fake-healthy" not in bench._FINAL.get("sections_soft_cancelled", "")


def test_section_exception_is_contained(fresh_final):
    """A failing diagnostic never sinks the artifact or later sections
    (reference behavior: errors become recorded skips, not stalls)."""
    wd = bench.Watchdog()
    ran = {}

    def boom():
        raise RuntimeError("diagnostic broke")

    def later():
        ran["later"] = True

    assert bench.run_section(wd, "fake-boom", boom, budget_s=30.0) is False
    bench.run_section(wd, "fake-after-boom", later, budget_s=30.0)
    assert ran.get("later") is True


def test_soft_cancel_grace_adapts_to_global_headroom(fresh_final):
    """The r5 tunnel-outage lesson: with global budget to spare, the
    post-soft-cancel grace rides out the stall (up to the cap) instead
    of exiting at the fixed floor; with the global deadline near, it
    stays at the floor so the clean exit still beats the global fire."""
    watchdogs = []

    def stalls():
        for _ in range(600):
            time.sleep(0.1)

    try:
        wd = bench.Watchdog()
        watchdogs.append(wd)
        bench.run_section(wd, "fake-grace-rich", stalls, budget_s=1.0)
        # fresh watchdog: the full global budget of headroom
        assert wd._grace_s == bench.ADAPTIVE_GRACE_CAP_S

        wd2 = bench.Watchdog()
        watchdogs.append(wd2)
        # headroom below floor + margin -> the floor wins, not ~80 s
        wd2._global_deadline = time.monotonic() + 200.0
        bench.run_section(wd2, "fake-grace-poor", stalls, budget_s=1.0)
        assert wd2._grace_s == bench.SOFT_CANCEL_GRACE_S
    finally:
        # leaked poller threads live until process exit; push their
        # global deadlines out so none can os._exit(0) mid-suite and
        # silently truncate a green pytest run
        for w in watchdogs:
            w._global_deadline = time.monotonic() + 10**9


# ---------------------------------------------------------------------------
# Baseline regression gate (ISSUE 13): synthetic artifact pair
# ---------------------------------------------------------------------------

def test_compare_baseline_flags_regressed_key_rows():
    baseline = {
        "value": 28197.1,
        "host_passthrough_fps": 100.0,
        "device_resnet50_fps": 1750.0,
        "host_datapath_copies_per_frame": 1.0,
        "host_datapath_allocs_per_frame": 0.0,
        "serving": {"gateway_p99_ms": 290.0},
        "wire_compression_best_ratio": 3.19,
        "replication_kill_lost": 0,
    }
    current = dict(baseline)
    current.update(
        {
            "host_passthrough_fps": 70.0,           # -30% fps: regression
            "device_resnet50_fps": 1745.0,          # -0.3%: within noise
            "host_datapath_copies_per_frame": 1.5,  # zero-copy pin broken
            "serving": {"gateway_p99_ms": 500.0},   # p99 blown
            "wire_compression_best_ratio": 3.1,     # -3%: within noise
            "replication_kill_lost": 2,             # lost frames: always
        }
    )
    regs = bench.compare_baseline(current, baseline)
    by_key = {r["key"]: r for r in regs}
    assert set(by_key) == {
        "host_passthrough_fps",
        "host_datapath_copies_per_frame",
        "serving.gateway_p99_ms",
        "replication_kill_lost",
    }
    assert by_key["host_passthrough_fps"]["rule"] == "fps"
    assert by_key["host_passthrough_fps"]["change_pct"] == -30.0
    assert by_key["serving.gateway_p99_ms"]["rule"] == "latency_ms"
    assert by_key["host_datapath_copies_per_frame"]["rule"] == "copies_per_frame"
    assert by_key["replication_kill_lost"]["rule"] == "lost_frames"


def test_compare_baseline_model_counterexamples_zero_tolerance():
    # ISSUE 18: one counterexample is a protocol bug, not noise — and a
    # fleet that stopped exhausting its bounds proves nothing
    baseline = {"lint": {"model": {"counterexamples": 0,
                                   "exhausted_all": True,
                                   "states": 1917}}}
    current = {"lint": {"model": {"counterexamples": 1,
                                  "exhausted_all": False,
                                  "states": 1917}}}
    by_key = {r["key"]: r for r in bench.compare_baseline(current, baseline)}
    assert by_key["lint.model.counterexamples"]["rule"] == \
        "model_counterexamples"
    assert by_key["lint.model.exhausted_all"]["rule"] == "model_exhausted"
    # states is informational, not gated
    assert "lint.model.states" not in by_key
    assert bench.compare_baseline(dict(baseline), dict(baseline)) == []


def test_compare_baseline_clean_pair_is_empty():
    art = {"host_passthrough_fps": 100.0, "value": 5.0,
           "serving": {"gateway_p99_ms": 290.0}}
    assert bench.compare_baseline(dict(art), dict(art)) == []
    # improvements are never regressions
    better = {"host_passthrough_fps": 140.0, "value": 9.0,
              "serving": {"gateway_p99_ms": 150.0}}
    assert bench.compare_baseline(better, art) == []


def test_load_baseline_accepts_driver_round_and_full_artifact(tmp_path):
    rnd = tmp_path / "BENCH_r99.json"
    rnd.write_text(json.dumps({"n": 99, "parsed": {"value": 1.0}}))
    assert bench.load_baseline_artifact(str(rnd)) == {"value": 1.0}
    full = tmp_path / "bench_full.json"
    full.write_text(json.dumps({"value": 2.0}))
    assert bench.load_baseline_artifact(str(full)) == {"value": 2.0}


def test_apply_baseline_gate_embeds_regressions(fresh_final, tmp_path):
    base = tmp_path / "b.json"
    base.write_text(json.dumps({"host_passthrough_fps": 100.0}))
    extras = bench._FINAL
    extras["host_passthrough_fps"] = 50.0
    bench.apply_baseline_gate(extras, str(base))
    assert extras["baseline_compared"]["regression_count"] == 1
    assert extras["regressions"][0]["key"] == "host_passthrough_fps"
    # the gate is data, never an exception — even on garbage input
    bench.apply_baseline_gate(extras, str(tmp_path / "missing.json"))
    assert "baseline_error" in extras
