"""Equivalence tests for the fused PeakNet-TPU encoder kernels.

The fused path (models/pallas_unet.py) must match the flax
``PeakNetUNetTPU(norm='frozen')`` oracle to bfloat16 tolerance; kernels
run in Pallas interpret mode on the CPU test backend (same math, same
padding logic, no Mosaic lowering) — the prescribed way to unit-test TPU
kernels off-hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from psana_ray_tpu.models import PeakNetUNetTPU
from psana_ray_tpu.models.pallas_unet import fused_conv_block, peaknet_tpu_fused_infer
from psana_ray_tpu.models.unet import ConvBlock
from psana_ray_tpu.models.resnet import _conv


def _rel_err(ref, got):
    ref = np.asarray(ref, np.float32)
    got = np.asarray(got, np.float32)
    return float(np.max(np.abs(ref - got)) / max(np.max(np.abs(ref)), 1e-3))


def _randomized(variables, key):
    leaves, treedef = jax.tree.flatten(variables)
    keys = jax.random.split(key, len(leaves))
    out = [
        l + 0.1 * jax.random.normal(k, l.shape, l.dtype)
        if hasattr(l, "dtype") and l.dtype == jnp.float32
        else l
        for l, k in zip(leaves, keys)
    ]
    return jax.tree.unflatten(treedef, out)


class TestFusedConvBlock:
    @pytest.mark.parametrize("cin,f,down", [(8, 16, True), (16, 16, False), (8, 8, True)])
    def test_matches_flax_block(self, rng, cin, f, down):
        import flax.linen as nn

        h, w = 8, 16

        class Level(nn.Module):
            @nn.compact
            def __call__(self, x):
                skip = ConvBlock(f, norm="frozen")(x)
                if down:
                    return skip, _conv(f, (3, 3), (2, 2), jnp.bfloat16)(skip)
                return skip, None

        x = jnp.asarray(rng.normal(size=(2, h, w, cin)).astype(np.float32) * 0.5)
        mod = Level()
        variables = _randomized(mod.init(jax.random.key(0), x), jax.random.key(1))
        skip_ref, down_ref = mod.apply(variables, x)

        from flax.core import meta

        p = meta.unbox(variables)["params"]
        bp = p["ConvBlock_0"]
        skip, dn = fused_conv_block(
            x,
            bp["Conv_0"]["kernel"],
            (bp["FrozenAffine_0"]["scale"], bp["FrozenAffine_0"]["bias"]),
            bp["Conv_1"]["kernel"],
            (bp["FrozenAffine_1"]["scale"], bp["FrozenAffine_1"]["bias"]),
            wd=p["Conv_0"]["kernel"] if down else None,
            interpret=True,
        )
        assert _rel_err(skip_ref, skip[..., :f]) < 0.05
        # padded channels must be exactly zero (the chaining contract)
        np.testing.assert_array_equal(np.asarray(skip[..., f:], np.float32), 0.0)
        if down:
            assert dn.shape[1:3] == (h // 2, w // 2)
            assert _rel_err(down_ref, dn[..., :f]) < 0.05
            np.testing.assert_array_equal(np.asarray(dn[..., f:], np.float32), 0.0)
        else:
            assert dn is None

    def test_chained_padded_input_is_exact(self, rng):
        """Levels chain in 128-lane-padded form: feeding a zero-padded
        input must give identical results to the unpadded one."""
        cin, f, h, w = 8, 8, 8, 16
        x = jnp.asarray(rng.normal(size=(1, h, w, cin)).astype(np.float32))
        w1 = jnp.asarray(rng.normal(size=(3, 3, cin, f)).astype(np.float32) * 0.2)
        w2 = jnp.asarray(rng.normal(size=(3, 3, f, f)).astype(np.float32) * 0.2)
        a = (jnp.ones((f,), jnp.float32), jnp.zeros((f,), jnp.float32))
        skip_a, _ = fused_conv_block(x, w1, a, w2, a, interpret=True)
        xp = jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, 128 - cin)))
        skip_b, _ = fused_conv_block(xp, w1, a, w2, a, interpret=True)
        np.testing.assert_array_equal(np.asarray(skip_a), np.asarray(skip_b))


class TestPeakNetTPUFusedInfer:
    def test_matches_flax_model(self, rng):
        # 64x128 keeps every inner level's extents even with w >= 8
        # (packed 32x64 -> 16x32 -> 8x16 -> bottleneck 4x8)
        features = (8, 16, 32, 32)
        model = PeakNetUNetTPU(features=features, norm="frozen")
        x = jnp.asarray(rng.normal(size=(1, 64, 128, 1)).astype(np.float32))
        variables = _randomized(model.init(jax.random.key(0), x), jax.random.key(1))
        ref = model.apply(variables, x)
        got = peaknet_tpu_fused_infer(
            variables, x, features=features, interpret=True
        )
        assert got.shape == ref.shape == (1, 64, 128, 1)
        assert _rel_err(ref, got) < 0.05

    def test_matches_flax_model_depth3(self, rng):
        features = (8, 16, 16)
        model = PeakNetUNetTPU(features=features, norm="frozen")
        x = jnp.asarray(rng.normal(size=(1, 32, 64, 2)).astype(np.float32))
        variables = _randomized(model.init(jax.random.key(0), x), jax.random.key(1))
        ref = model.apply(variables, x)
        got = peaknet_tpu_fused_infer(
            variables, x, features=features, interpret=True
        )
        assert _rel_err(ref, got) < 0.05
