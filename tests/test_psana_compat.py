"""Contract tests for the psana adapter against a mock psana module.

The reference's only oracle for this surface was live LCLS operation
(reference ``README.md:20``); off-site, the testable equivalent is a fake
``psana`` exercising the adapter's contracts: damaged-event None handling
must consume the event index (reference parity: ``producer.py:88`` counts
a local idx; ours must stay globally aligned), eV→keV conversion, missing
ebeam readings, shard striding × ``start_event`` interplay, and mask dtype.
"""

import sys
import types

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _evict_fake_bound_adapter():
    """monkeypatch restores sys.modules['psana'], but the adapter module
    imported DURING the test stays cached with the fake bound inside it —
    a later test's `open_source('mfx…')` would then succeed against the
    fake instead of raising. Evict it so every test re-imports fresh."""
    yield
    sys.modules.pop("psana_ray_tpu.sources.psana_compat", None)


class _FakeRaw:
    """det.raw facade: calib/image/raw per event + bad-pixel mask."""

    def __init__(self, frames, damaged):
        self.frames = frames  # event_idx -> array
        self.damaged = set(damaged)

    def calib(self, evt):
        return None if evt.idx in self.damaged else self.frames[evt.idx]

    def image(self, evt):
        f = self.calib(evt)
        return None if f is None else f.sum(axis=0)  # assembled 2-D stand-in

    def raw(self, evt):
        return self.calib(evt)

    def mask(self, calib_const=True, status=True):
        assert calib_const and status  # the adapter requests both sources
        m = np.ones(self.frames[0].shape, dtype=bool)
        m[..., 0] = False
        return m


class _FakeEbeamRaw:
    def __init__(self, energies_ev):
        self.energies_ev = energies_ev

    def ebeamPhotonEnergy(self, evt):
        return self.energies_ev.get(evt.idx)  # None when the reading is absent


class _Evt:
    def __init__(self, idx):
        self.idx = idx


class _FakeRun:
    def __init__(self, frames, damaged, energies_ev):
        self._frames, self._damaged, self._energies = frames, damaged, energies_ev

    def Detector(self, name):
        det = types.SimpleNamespace()
        det.raw = (
            _FakeEbeamRaw(self._energies)
            if name == "ebeam"
            else _FakeRaw(self._frames, self._damaged)
        )
        return det

    def events(self):
        return iter(_Evt(i) for i in range(len(self._frames)))


def _install_fake_psana(monkeypatch, n_events=12, damaged=(), energies_ev=None):
    frames = [
        np.full((2, 4, 4), float(i), dtype=np.float64) for i in range(n_events)
    ]
    energies = energies_ev if energies_ev is not None else {
        i: 9500.0 + i for i in range(n_events)
    }

    fake = types.ModuleType("psana")

    def DataSource(exp=None, run=None):
        ds = types.SimpleNamespace()
        ds.runs = lambda: iter([_FakeRun(frames, damaged, energies)])
        return ds

    fake.DataSource = DataSource
    monkeypatch.setitem(sys.modules, "psana", fake)
    # fresh import under the fake (a real psana would have failed at import)
    monkeypatch.delitem(sys.modules, "psana_ray_tpu.sources.psana_compat", raising=False)
    from psana_ray_tpu.sources.psana_compat import PsanaSource

    return PsanaSource


class TestPsanaContract:
    def test_indices_are_global_and_energy_is_kev(self, monkeypatch):
        PsanaSource = _install_fake_psana(monkeypatch, n_events=6)
        src = PsanaSource("mfxl1038923", 58, "epix10k2M")
        out = list(src.iter_indexed_events("calib"))
        assert [i for i, _, _ in out] == [0, 1, 2, 3, 4, 5]
        # eV reading / 1000 -> keV (reference units: photon_energy in keV)
        assert out[0][2] == pytest.approx(9.5)
        assert out[5][2] == pytest.approx(9.505)
        # frames come back float32 regardless of psana's float64
        assert all(d.dtype == np.float32 for _, d, _ in out)

    def test_damaged_event_consumes_index_but_is_skipped(self, monkeypatch):
        PsanaSource = _install_fake_psana(monkeypatch, n_events=6, damaged=(2, 3))
        src = PsanaSource("x", 1, "det")
        idxs = [i for i, _, _ in src.iter_indexed_events("calib")]
        # 2 and 3 are gone but LATER indices are unshifted — the global
        # event number is the resume/provenance key, so a damaged event
        # must not renumber the stream
        assert idxs == [0, 1, 4, 5]

    def test_missing_ebeam_reading_maps_to_zero(self, monkeypatch):
        PsanaSource = _install_fake_psana(
            monkeypatch, n_events=2, energies_ev={0: None, 1: 8000.0}
        )
        src = PsanaSource("x", 1, "det")
        out = list(src.iter_indexed_events("calib"))
        assert out[0][2] == 0.0
        assert out[1][2] == pytest.approx(8.0)

    def test_shard_striding_with_damage(self, monkeypatch):
        PsanaSource = _install_fake_psana(monkeypatch, n_events=10, damaged=(3,))
        a = PsanaSource("x", 1, "det", shard_rank=0, num_shards=2)
        b = PsanaSource("x", 1, "det", shard_rank=1, num_shards=2)
        ia = [i for i, _, _ in a.iter_indexed_events("calib")]
        ib = [i for i, _, _ in b.iter_indexed_events("calib")]
        assert ia == [0, 2, 4, 6, 8]
        assert ib == [1, 5, 7, 9]  # 3 damaged: skipped, not renumbered
        assert not set(ia) & set(ib)  # disjoint shards

    def test_start_event_composes_with_sharding(self, monkeypatch):
        PsanaSource = _install_fake_psana(monkeypatch, n_events=12)
        src = PsanaSource("x", 1, "det", shard_rank=1, num_shards=3, start_event=5)
        idxs = [i for i, _, _ in src.iter_indexed_events("calib")]
        # shard 1 of 3 owns 1, 4, 7, 10; start_event=5 keeps >= 5
        assert idxs == [7, 10]

    def test_image_mode_and_raw_mode_dispatch(self, monkeypatch):
        PsanaSource = _install_fake_psana(monkeypatch, n_events=2)
        src = PsanaSource("x", 1, "det")
        img = next(iter(src.iter_indexed_events("image")))[1]
        assert img.ndim == 2  # assembled image, not a panel stack
        rawd = next(iter(src.iter_indexed_events("raw")))[1]
        assert rawd.ndim == 3

    def test_bad_pixel_mask_is_uint8(self, monkeypatch):
        PsanaSource = _install_fake_psana(monkeypatch)
        src = PsanaSource("x", 1, "det")
        mask = src.create_bad_pixel_mask()
        assert mask.dtype == np.uint8
        assert mask.shape == (2, 4, 4)
        assert mask[..., 0].max() == 0 and mask[..., 1].min() == 1

    def test_open_source_dispatches_to_psana_backend(self, monkeypatch):
        _install_fake_psana(monkeypatch, n_events=4)
        from psana_ray_tpu.sources import open_source

        src = open_source("mfxl1038923", 58, "epix10k2M", shard_rank=0, num_shards=1)
        assert [i for i, _, _ in src.iter_indexed_events("calib")] == [0, 1, 2, 3]
