"""Credit-based streaming transport (ISSUE 5): server-push delivery,
windowed pipelined PUT, bounded server-side waits, crash-redelivery
under streaming, and RTT-independence through a delay-injecting proxy.

The delivery guarantees under test are exactly the request/response
path's, restated for explicit acks: at-least-once (duplicates possible
after a crash, silent loss never), FIFO per connection, no holes in a
windowed put stream across reconnects.
"""

import socket
import threading
import time
from collections import deque

import numpy as np
import pytest

from psana_ray_tpu.records import EndOfStream, FrameRecord
from psana_ray_tpu.transport import EMPTY, TransportClosed
from psana_ray_tpu.transport.ring import RingBuffer
from psana_ray_tpu.transport.tcp import STREAM, TcpQueueClient, TcpQueueServer

from faultproxy import DelayProxy


def _rec(idx, shape=(1, 8, 8), rank=0):
    return FrameRecord(rank, idx, np.full(shape, float(idx), np.float32), 1.0)


def _mk(maxsize=64):
    q = RingBuffer(maxsize)
    srv = TcpQueueServer(q, host="127.0.0.1").serve_background()
    return q, srv


def _drain_plain(port, n, timeout=5.0):
    """Pull up to ``n`` frames over a fresh request/response client."""
    c = TcpQueueClient("127.0.0.1", port)
    out = []
    deadline = time.monotonic() + timeout
    while len(out) < n and time.monotonic() < deadline:
        out.extend(c.get_batch(n - len(out), timeout=0.5))
    c.disconnect()
    return out


class TestStreamBasics:
    def test_stream_delivers_fifo(self):
        q, srv = _mk()
        try:
            for i in range(10):
                q.put(_rec(i))
            c = TcpQueueClient("127.0.0.1", srv.port)
            c.stream_open(window=32)
            got = []
            while len(got) < 10:
                got.extend(c.get_batch_stream(10 - len(got), timeout=2.0))
            assert [r.event_idx for r in got] == list(range(10))
            c.disconnect()
        finally:
            srv.shutdown()

    def test_stream_serves_frames_produced_after_subscribe(self):
        # no empty-queue poll round trips: the push arrives as the frame does
        q, srv = _mk()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            c.stream_open(window=8)
            t = threading.Timer(0.15, lambda: q.put(_rec(7)))
            t.start()
            t0 = time.monotonic()
            out = c.get_batch_stream(1, timeout=3.0)
            assert out and out[0].event_idx == 7
            assert time.monotonic() - t0 < 1.5  # pushed, not polled at 1 Hz
            t.join()
            c.disconnect()
        finally:
            srv.shutdown()

    def test_get_wait_and_get_route_through_stream(self):
        q, srv = _mk()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            c.stream_open(window=8)
            assert c.get() is EMPTY  # nothing pushed yet
            q.put(_rec(3))
            rec = c.get_wait(timeout=2.0)
            assert rec is not EMPTY and rec.event_idx == 3
            c.disconnect()
        finally:
            srv.shutdown()

    def test_queue_close_ends_stream_with_transport_closed(self):
        q, srv = _mk()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            c.stream_open(window=8)
            q.close()
            with pytest.raises(TransportClosed):
                for _ in range(50):  # 'X' arrives once the pop loop sees it
                    c.get_batch_stream(1, timeout=0.2)
            c.disconnect()
        finally:
            srv.shutdown()

    def test_put_and_probes_route_over_side_channel(self):
        q, srv = _mk()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            c.stream_open(window=8)
            # a put on the streamed socket itself would desync the push
            # framing — it must transparently use a second connection
            assert c.put(_rec(42))
            assert c.size() == 1 or c.get_wait(timeout=2.0).event_idx == 42
            c.disconnect()
        finally:
            srv.shutdown()


class TestCrashRedeliveryStreaming:
    """ISSUE 5 acceptance: kill a streaming consumer mid-window and every
    un-ACKed frame redelivers to a second consumer — duplicates allowed,
    loss never."""

    def _put_and_push_all(self, q, srv, n, window=32):
        base = STREAM.stats()["frames_pushed_total"]  # counter is process-wide
        for i in range(n):
            q.put(_rec(i))
        c = TcpQueueClient("127.0.0.1", srv.port)
        c.stream_open(window=window)
        deadline = time.monotonic() + 5.0
        while (
            STREAM.stats()["frames_pushed_total"] - base < n
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)  # wait for every frame to be pushed (into the
            # client socket buffer) so the ack arithmetic below is exact
        return c

    def test_kill_mid_window_redelivers_everything_unacked(self):
        q, srv = _mk()
        try:
            c = self._put_and_push_all(q, srv, 10)
            got = c.get_batch_stream(6, timeout=2.0)  # consumed, NOT yet acked
            assert len(got) == 6
            c._sock.close()  # crash: no BYE, no ack ever sent
            deadline = time.monotonic() + 5.0
            while q.size() < 10 and time.monotonic() < deadline:
                time.sleep(0.01)
            # nothing was acked: all 10 redeliver (the 6 consumed ones as
            # duplicates — at-least-once chooses duplication over loss)
            out = _drain_plain(srv.port, 10)
            assert sorted(r.event_idx for r in out) == list(range(10))
        finally:
            srv.shutdown()

    def test_kill_after_partial_ack_redelivers_exactly_the_tail(self):
        q, srv = _mk()
        try:
            c = self._put_and_push_all(q, srv, 10)
            first = c.get_batch_stream(6, timeout=2.0)
            assert len(first) == 6
            # coming back for more acks the previous 6 (consumption ack)
            second = c.get_batch_stream(1, timeout=2.0)
            assert len(second) == 1 and second[0].event_idx == 6
            c._sock.close()  # crash with seq 7..10 un-ACKed
            deadline = time.monotonic() + 5.0
            while q.size() < 4 and time.monotonic() < deadline:
                time.sleep(0.01)
            out = _drain_plain(srv.port, 4)
            # frames 0..5 were acked (never redelivered); 6 was delivered
            # but not acked (redelivered as a duplicate); 7..9 undelivered
            assert sorted(r.event_idx for r in out) == [6, 7, 8, 9]
        finally:
            srv.shutdown()

    def test_clean_disconnect_acks_consumed_no_redelivery(self):
        q, srv = _mk()
        try:
            c = self._put_and_push_all(q, srv, 5)
            got = []
            while len(got) < 5:
                got.extend(c.get_batch_stream(5 - len(got), timeout=2.0))
            c.disconnect()  # final cumulative ack + BYE
            time.sleep(0.3)
            assert q.size() == 0  # no duplicates on a clean goodbye
        finally:
            srv.shutdown()

    def test_reconnect_mid_stream_resumes_without_loss(self):
        q, srv = _mk()
        try:
            c = self._put_and_push_all(q, srv, 12)
            got = {r.event_idx for r in c.get_batch_stream(4, timeout=2.0)}
            assert len(got) == 4
            c._sock.close()  # network drop under the reader
            deadline = time.monotonic() + 10.0
            while len(got) < 12 and time.monotonic() < deadline:
                for r in c.get_batch_stream(12, timeout=0.5):
                    got.add(r.event_idx)  # duplicates collapse in the set
            # the fresh subscription (credits intact: same window) redelivers
            # everything the dead connection had un-ACKed — zero loss
            assert got == set(range(12))
            c.disconnect()
        finally:
            srv.shutdown()


class TestWindowedPut:
    def test_pipelined_puts_are_fifo_and_flush_blocks_for_acks(self):
        q, srv = _mk()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            for i in range(20):
                assert c.put_pipelined(_rec(i), deadline=time.monotonic() + 10)
            assert c.flush_puts(deadline=time.monotonic() + 10)
            drained = [q.get().event_idx for _ in range(20)]
            assert drained == list(range(20))
            c.disconnect()
        finally:
            srv.shutdown()

    def test_reconnect_resends_exactly_the_unacked_tail_no_holes(self):
        q, srv = _mk()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            for i in range(3):
                assert c.put_pipelined(_rec(i), deadline=time.monotonic() + 10)
            c._sock.close()  # drop with acks unread: tail 0..2 unconfirmed
            for i in range(3, 6):
                assert c.put_pipelined(_rec(i), deadline=time.monotonic() + 10)
            assert c.flush_puts(deadline=time.monotonic() + 10)
            out = []
            while q.size():
                out.append(q.get().event_idx)
            # no holes ever; duplicates tolerated (resend of enqueued-but-
            # unacked puts is at-least-once by design)
            assert sorted(set(out)) == list(range(6))
            assert len(out) >= 6
            assert STREAM.stats()["put_resent_total"] >= 3
            c.disconnect()
        finally:
            srv.shutdown()

    def test_window_full_blocks_then_backpressure_releases(self):
        q, srv = _mk(maxsize=4)
        try:
            c = TcpQueueClient("127.0.0.1", srv.port, put_window=4)
            stop = threading.Event()
            drained = []

            def consume():
                while not stop.is_set() and len(drained) < 12:
                    item = q.get_wait(timeout=0.2)
                    if item is not EMPTY:
                        drained.append(item.event_idx)
                        time.sleep(0.02)  # slow consumer: forces backpressure

            t = threading.Thread(target=consume, daemon=True)
            t.start()
            for i in range(12):
                while not c.put_pipelined(_rec(i), deadline=time.monotonic() + 0.3):
                    pass  # window full: bounded slices, like the producer CLI
            assert c.flush_puts(deadline=time.monotonic() + 10)
            t.join(timeout=10)
            stop.set()
            assert drained == list(range(12))
            c.disconnect()
        finally:
            srv.shutdown()

    def test_windowed_put_survives_server_restart(self):
        """Review fix: put_pipelined's deadline bounds the wait for
        window space, NOT the reconnect envelope — a supervisor
        restarting the queue server mid-window must be ridden out (the
        old short-deadline reconnect raised TransportClosed and the
        producer declared the stream dead)."""
        q1, srv1 = _mk()
        port = srv1.port
        c = TcpQueueClient(
            "127.0.0.1", port, reconnect_tries=8, reconnect_base_s=0.1
        )
        assert c.put_pipelined(_rec(0), deadline=time.monotonic() + 5)
        assert c.flush_puts(deadline=time.monotonic() + 10)
        srv1.shutdown()
        holder = {}

        def restart():
            time.sleep(0.4)
            holder["q"] = RingBuffer(64)
            holder["srv"] = TcpQueueServer(
                holder["q"], host="127.0.0.1", port=port
            ).serve_background()

        threading.Thread(target=restart, daemon=True).start()
        # the send fails against the dead server; the reconnect must
        # wait the restart out (producer-CLI-style bounded slices)
        while not c.put_pipelined(_rec(1), deadline=time.monotonic() + 0.5):
            pass
        assert c.flush_puts(deadline=time.monotonic() + 10)
        try:
            got = [r.event_idx for r in holder["q"].get_batch(8, timeout=2.0)]
            assert 1 in got  # delivered to the restarted server, no holes
            c.disconnect()
        finally:
            holder["srv"].close_all()
            holder["srv"].shutdown()

    def test_backpressure_beyond_socket_timeout_is_not_treated_as_death(self):
        """Review fix: an overdue windowed-put ack is BACKPRESSURE (the
        server's blocking enqueue against a full queue), not a dead
        connection — the old behavior reconnected on the socket timeout
        and resent the whole window into the already-full queue,
        amplifying duplicates every timeout_s."""
        q, srv = _mk(maxsize=1)
        try:
            base_resent = STREAM.stats()["put_resent_total"]
            # tiny socket timeout: the ack delay WILL exceed it
            c = TcpQueueClient("127.0.0.1", srv.port, timeout_s=0.3, put_window=2)
            assert c.put_pipelined(_rec(0), deadline=time.monotonic() + 5)
            assert c.put_pipelined(_rec(1), deadline=time.monotonic() + 5)
            # queue holds 1; frame 1's enqueue (and ack) now blocks.
            # Hold it full for several socket-timeout periods, then free.
            done = {}

            def flush():
                done["ok"] = c.flush_puts(deadline=time.monotonic() + 10)

            t = threading.Thread(target=flush, daemon=True)
            t.start()
            time.sleep(1.0)  # > 3x timeout_s of ack silence
            assert q.get().event_idx == 0  # space frees; ack flows
            t.join(timeout=10)
            assert done.get("ok") is True
            assert q.get_wait(timeout=5.0).event_idx == 1
            # no spurious redelivery: the quiet wire never reconnected
            assert STREAM.stats()["put_resent_total"] == base_resent
            assert q.size() == 0  # and no duplicate of frame 1 arrives
            c.disconnect()
        finally:
            srv.shutdown()

    def test_dead_client_mid_enqueue_wait_is_detected_and_dropped(self):
        """Review fix: a serve thread blocked enqueueing a windowed put
        against a full queue must notice the client dying (liveness
        probe between slices) instead of pinning the thread + the
        frame's pooled lease forever and enqueueing the orphan frame
        arbitrarily late on top of the reconnect resend."""
        q, srv = _mk(maxsize=1)
        try:
            c = TcpQueueClient("127.0.0.1", srv.port, put_window=4)
            assert c.put_pipelined(_rec(0), deadline=time.monotonic() + 5)
            assert c.put_pipelined(_rec(1), deadline=time.monotonic() + 5)
            time.sleep(0.3)  # server now blocked enqueueing frame 1
            c._sock.close()  # client dies mid-window, no reconnect follows
            time.sleep(1.2)  # > 2 enqueue slices: probe must fire
            assert q.get().event_idx == 0  # frees the slot
            # the dead client's frame must NOT appear now that space exists
            assert q.get_wait(timeout=1.0) is EMPTY
        finally:
            srv.shutdown()

    def test_other_opcodes_drain_the_window_first(self):
        q, srv = _mk()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            for i in range(5):
                assert c.put_pipelined(_rec(i), deadline=time.monotonic() + 10)
            # a request issued over the outstanding window would read a
            # put ack as its own status — size() must drain first
            assert c.size() == 5
            assert not c._put_unacked
            c.disconnect()
        finally:
            srv.shutdown()


class _CountingRing(RingBuffer):
    """Counts server-side ops so the tests can assert round-trip economy."""

    def __init__(self, maxsize):
        super().__init__(maxsize)
        self.batch_calls = 0
        self.put_wait_calls = 0

    def get_batch(self, max_items, timeout=None):
        self.batch_calls += 1
        return super().get_batch(max_items, timeout=timeout)

    def put_wait(self, item, timeout=None):
        self.put_wait_calls += 1
        return super().put_wait(item, timeout=timeout)


class TestBoundedServerSideWaits:
    """Satellites 1+2: an empty (or full) queue must cost one round trip
    per server-side wait interval, not one per 1 ms client poll tick."""

    def test_empty_get_batch_waits_server_side(self):
        q = _CountingRing(8)
        srv = TcpQueueServer(q, host="127.0.0.1").serve_background()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            t0 = time.monotonic()
            assert c.get_batch(4, timeout=0.6) == []
            dt = time.monotonic() - t0
            assert dt >= 0.5  # honored the timeout...
            # ...with ~1 blocking server call, not ~600 polls (the old
            # hardcoded 1 ms sleep + full GET round trip per tick)
            assert q.batch_calls <= 4, q.batch_calls
            c.disconnect()
        finally:
            srv.shutdown()

    def test_get_batch_wakes_promptly_when_item_arrives(self):
        q = _CountingRing(8)
        srv = TcpQueueServer(q, host="127.0.0.1").serve_background()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            threading.Timer(0.15, lambda: q.put(_rec(1))).start()
            t0 = time.monotonic()
            out = c.get_batch(4, timeout=3.0)
            dt = time.monotonic() - t0
            assert [r.event_idx for r in out] == [1]
            assert dt < 1.0  # server-side condition wake, no poll latency
            c.disconnect()
        finally:
            srv.shutdown()

    def test_get_batch_poll_cadence_is_a_parameter(self):
        # the retry loop's pacing is poll_s now, not a hardcoded 1 ms
        import inspect

        sig = inspect.signature(TcpQueueClient.get_batch)
        assert "poll_s" in sig.parameters

    def test_full_put_wait_waits_server_side(self):
        q = _CountingRing(2)
        srv = TcpQueueServer(q, host="127.0.0.1").serve_background()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            assert c.put(_rec(0)) and c.put(_rec(1))  # full
            t0 = time.monotonic()
            assert c.put_wait(_rec(2), timeout=0.6) is False
            dt = time.monotonic() - t0
            assert dt >= 0.5
            assert q.put_wait_calls <= 4, q.put_wait_calls
            c.disconnect()
        finally:
            srv.shutdown()

    def test_full_put_wait_wakes_when_space_frees(self):
        q = _CountingRing(2)
        srv = TcpQueueServer(q, host="127.0.0.1").serve_background()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            assert c.put(_rec(0)) and c.put(_rec(1))
            threading.Timer(0.15, q.get).start()
            t0 = time.monotonic()
            assert c.put_wait(_rec(2), timeout=3.0)
            assert time.monotonic() - t0 < 1.0
            c.disconnect()
        finally:
            srv.shutdown()


class TestStreamingDataReader:
    def test_iter_records_over_streaming_reader_with_duplicate_eos(self):
        from psana_ray_tpu.consumer import DataReader

        _, srv = _mk()
        try:
            # DataReader binds the NAMED queue from its config defaults
            q = srv.open_named("default", "shared_queue")
            for i in range(10):
                q.put(_rec(i))
            # two producer runtimes' EOS coverage, with a duplicate copy
            # of runtime 0's marker (destined for a sibling consumer)
            q.put(EndOfStream(producer_rank=0, shards_done=1, total_shards=2))
            q.put(EndOfStream(producer_rank=0, shards_done=1, total_shards=2))
            q.put(EndOfStream(producer_rank=1, shards_done=1, total_shards=2))
            reader = DataReader(
                address=f"tcp://127.0.0.1:{srv.port}", streaming=True
            ).connect()
            got = [r.event_idx for r in reader.iter_records()]
            assert got == list(range(10))
            reader.close()
            # the duplicate marker was HELD and returned via the side
            # channel (a put on the streamed socket would desync it) so
            # the sibling consumer still completes
            deadline = time.monotonic() + 3.0
            while q.size() < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert q.size() == 1
        finally:
            srv.shutdown()

    def test_batches_from_queue_prefers_stream_drain(self):
        from psana_ray_tpu.infeed.batcher import batches_from_queue

        q, srv = _mk()
        try:
            cons = TcpQueueClient("127.0.0.1", srv.port)

            def produce():
                for i in range(16):
                    q.put(_rec(i))
                q.put(EndOfStream(total_events=16))

            threading.Thread(target=produce, daemon=True).start()
            seen = []
            for batch in batches_from_queue(cons, 4, poll_interval_s=0.01):
                seen.extend(batch.event_idx[: batch.num_valid].tolist())
            assert seen == list(range(16))
            # the drain subscribed a stream (the preference, not a fallback)
            assert cons._stream is not None
            cons.disconnect()
        finally:
            srv.shutdown()


# DelayProxy moved to tests/faultproxy.py (ISSUE 8): the delay-line
# proxy grew into the reusable fault-injection harness (kill-at-byte,
# torn-write, stall) that drives the durability recovery tests too.


class _CountingSock:
    """Delegating socket wrapper counting upstream (client->server)
    messages — the deterministic form of RTT-independence: round trips
    per frame, not wall clock (which measures the CI box's scheduler)."""

    def __init__(self, sock):
        self._sock = sock
        self.sends = 0

    def sendall(self, *a, **kw):
        self.sends += 1
        return self._sock.sendall(*a, **kw)

    def sendmsg(self, *a, **kw):
        self.sends += 1
        return self._sock.sendmsg(*a, **kw)

    def __getattr__(self, name):
        return getattr(self._sock, name)


class TestRttIndependence:
    """ISSUE 5 acceptance: through a 5 ms-each-way delay proxy, streaming
    must sustain >=10x the request/response throughput on the same
    frames — the push pipeline hides the RTT under transfer while the
    pull path pays ~1 RTT per frame. The wall-clock ratios are measured
    under ``slow`` (a shared 2-core CI box's scheduler episodically adds
    multi-ms per-frame noise that measures the box, not the transport);
    the tier-1 pin below counts round trips instead, which is the
    mechanism and is deterministic."""

    def test_stream_drain_needs_no_per_frame_round_trips(self):
        n = 40
        q, srv = _mk(maxsize=2 * n)
        try:
            frames = [_rec(i, shape=(2, 32, 32)) for i in range(n)]
            # request/response: one upstream request per get_wait
            for f in frames:
                q.put(f)
            rr = TcpQueueClient("127.0.0.1", srv.port)
            rr_sock = _CountingSock(rr._sock)
            rr._sock = rr_sock
            for _ in range(n):
                assert rr.get_wait(timeout=5.0) is not EMPTY
            assert rr_sock.sends >= n  # the pull path's per-frame RTT
            rr.disconnect()
            # streaming: upstream traffic is ONE subscribe + a handful of
            # cumulative acks, regardless of n — that absence of
            # per-frame requests is exactly what the delay proxy turns
            # into the >=10x wall-clock win
            for f in frames:
                q.put(f)
            st = TcpQueueClient("127.0.0.1", srv.port)
            st_sock = _CountingSock(st._sock)
            st._sock = st_sock
            st.stream_open(window=2 * n)
            time.sleep(0.5)  # let the pushes land in the socket buffer
            got = 0
            while got < n:
                out = st.get_batch_stream(n - got, timeout=5.0)
                assert out, "stream starved"
                got += len(out)
            assert st_sock.sends * 4 <= rr_sock.sends, (
                f"streamed drain sent {st_sock.sends} upstream messages "
                f"for {n} frames vs {rr_sock.sends} request/response "
                f"round trips — the stream should be round-trip-free"
            )
            st.disconnect()
        finally:
            srv.shutdown()

    def _measure_ratio(self, frames, n, delay_s, window, rr_timeout=5.0):
        """One full comparison: (t_rr, t_stream) through a fresh server +
        proxy pair. Streaming is best-of-3 passes — scheduler noise on a
        shared CI box only ever SLOWS a pass, never speeds it past the
        physics."""
        q, srv = _mk(maxsize=4 * n)
        proxy = DelayProxy("127.0.0.1", srv.port, delay_s=delay_s)
        try:
            for i in range(n):
                q.put(frames[i % len(frames)])
            rr = TcpQueueClient("127.0.0.1", proxy.port)
            t0 = time.monotonic()
            for _ in range(n):
                assert rr.get_wait(timeout=rr_timeout) is not EMPTY, "r/r starved"
            t_rr = time.monotonic() - t0
            rr.disconnect()
            t_stream = None
            for _ in range(3):
                for i in range(n):
                    q.put(frames[i % len(frames)])
                st = TcpQueueClient("127.0.0.1", proxy.port)
                st.stream_open(window=window)
                t0 = time.monotonic()
                got = 0
                while got < n:
                    out = st.get_batch_stream(n - got, timeout=rr_timeout)
                    assert out or time.monotonic() - t0 < 10, "stream starved"
                    got += len(out)
                dt = time.monotonic() - t0
                st.disconnect()
                t_stream = dt if t_stream is None else min(t_stream, dt)
            return t_rr, t_stream
        finally:
            proxy.close()
            srv.shutdown()

    @pytest.mark.slow
    def test_streaming_10x_request_response_through_5ms_proxy(self):
        import sys

        n = 50
        shape = (2, 64, 64)  # 16 KB u16 frames: transfer time << RTT
        frames = [
            FrameRecord(0, i, np.full(shape, i % 7, np.uint16), 1.0)
            for i in range(n)
        ]
        # the proxy's pump threads must not be starved by the drain loop:
        # Python's default 5 ms GIL switch interval quantizes chunk relay
        # to ~5 ms steps on a small box, which measures the SCHEDULER, not
        # the transport (the r/r path is sleep-dominated and unaffected)
        old_switch = sys.getswitchinterval()
        sys.setswitchinterval(0.0005)
        try:
            best = None
            for _attempt in range(3):  # scheduler-noise episodes last
                # seconds on this box; a fresh measurement escapes them
                t_rr, t_stream = self._measure_ratio(
                    frames, n, delay_s=0.005, window=2 * n
                )
                assert t_rr >= n * 2 * 0.005 * 0.8  # RTT actually paid
                ratio = t_rr / t_stream
                best = ratio if best is None else max(best, ratio)
                if best >= 10:
                    break
            assert best >= 10, (
                f"streaming only {best:.1f}x the request/response "
                f"throughput through the 5 ms proxy (expected >=10x; "
                f"measured 14-36x on an idle box)"
            )
        finally:
            sys.setswitchinterval(old_switch)

    @pytest.mark.slow
    def test_streaming_removes_the_rtt_tax_on_epix_frames(self):
        """Full-size epix u16 frames (4.33 MB) through the same 5 ms
        proxy: here transfer time through a Python relay on this box
        (~7 ms/frame) is commensurate with the RTT, so the theoretical
        streaming win is (RTT + transfer)/transfer ≈ 2.5x, not 10x — the
        10x regime needs RTT >> transfer (the 16 KB test above, or real
        NICs at multi-GB/s; PERF_NOTES has the arithmetic). What MUST
        hold at frame scale: streaming removes the RTT tax (well above
        the no-pipelining baseline) and never regresses to it."""
        n = 24
        shape = (16, 352, 384)
        rng = np.random.default_rng(7)
        frames = [
            FrameRecord(0, i, rng.integers(0, 4096, size=shape, dtype=np.uint16), 1.0)
            for i in range(4)
        ]
        best = None
        for _attempt in range(3):
            # window ~2 batches in flight: a huge window just bloats the
            # proxy's delay line with undelivered frames
            t_rr, t_stream = self._measure_ratio(
                frames, n, delay_s=0.005, window=8, rr_timeout=10.0
            )
            assert t_rr >= n * 2 * 0.005 * 0.8  # the pull path paid the RTT
            best = t_rr / t_stream if best is None else max(best, t_rr / t_stream)
            if best >= 1.5:
                break
        assert best >= 1.5, (
            f"streaming only {best:.2f}x request/response on epix frames "
            f"— the ~10 ms/frame RTT tax should be gone (measured ~2.5x)"
        )
