"""Device-trace utilities (SURVEY.md §5 tracing/profiling)."""

import os

import jax
import jax.numpy as jnp

from psana_ray_tpu.utils.trace import annotate, trace


def _tree_files(root):
    return [
        os.path.join(d, f) for d, _, files in os.walk(root) for f in files
    ]


class TestTrace:
    def test_trace_captures_profile(self, tmp_path):
        logdir = str(tmp_path / "prof")
        with trace(logdir):
            with annotate("test.region"):
                y = jax.jit(lambda x: x * 2 + 1)(jnp.arange(8.0))
                jax.block_until_ready(y)
        files = _tree_files(logdir)
        assert files, "trace produced no profile files"

    def test_none_logdir_is_noop(self):
        with trace(None):
            pass  # no jax import side effects required

    def test_annotate_outside_trace_is_safe(self):
        with annotate("outside"):
            x = jnp.ones(4) + 1
        assert float(x.sum()) == 8.0
