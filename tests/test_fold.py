"""Train→serve continuity: norm='batch' training form folds EXACTLY into
the norm='frozen' serving form (models/fold.py).

This is the supported route from a trained checkpoint to the parameter
form every fused serving kernel consumes — the capability the reference's
mission statement implies ("Stream psana data ... for ... inference",
reference ``project.toml:4``) but never builds.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psana_ray_tpu.models import (
    PeakNetUNetTPU,
    ResNet18,
    fold_batchnorm,
)


def _train_mode_stats(model, x, steps=3, key=0):
    """Init a norm='batch' model and run a few train-mode passes so the
    running statistics move away from their (0, 1) init — the fold must
    be exact for NON-trivial stats."""
    variables = model.init(jax.random.key(key), x)
    for i in range(steps):
        xi = x + 0.3 * jax.random.normal(jax.random.key(100 + i), x.shape, x.dtype)
        _, mutated = model.apply(variables, xi, mutable=("batch_stats",))
        variables = {**variables, **mutated}
    return variables


class TestFoldResNet:
    def test_fold_matches_eval_batchnorm_exactly(self, rng):
        # f32 end to end so the only differences are op-ordering ulps
        train_model = ResNet18(num_classes=2, width=8, norm="batch", dtype=jnp.float32)
        x = jnp.asarray(rng.normal(size=(4, 32, 32, 2)).astype(np.float32))
        variables = _train_mode_stats(train_model, x)
        assert "batch_stats" in variables  # the form fold consumes

        eval_model = ResNet18(num_classes=2, width=8, norm="batch_eval", dtype=jnp.float32)
        ref = eval_model.apply(variables, x)

        folded = fold_batchnorm(variables)
        frozen_model = ResNet18(num_classes=2, width=8, norm="frozen", dtype=jnp.float32)
        got = frozen_model.apply(folded, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-4, atol=1e-4)

    def test_folded_tree_is_frozen_layout(self, rng):
        # the folded tree must be structurally identical to a norm='frozen'
        # init — that's what makes it consumable by the fused kernels'
        # _block_params extractors without any adaptation
        from flax.core import meta

        train_model = ResNet18(num_classes=2, width=8, norm="batch")
        x = jnp.zeros((2, 32, 32, 2))
        folded = fold_batchnorm(train_model.init(jax.random.key(0), x))
        frozen = meta.unbox(
            ResNet18(num_classes=2, width=8, norm="frozen").init(jax.random.key(0), x)
        )
        assert jax.tree_util.tree_structure(folded) == jax.tree_util.tree_structure(frozen)

    def test_fold_requires_batch_stats(self):
        with pytest.raises(ValueError, match="batch_stats"):
            fold_batchnorm({"params": {}})


class TestFoldPeakNetTPU:
    def test_fold_matches_eval_batchnorm_exactly(self, rng):
        features = (8, 16)
        train_model = PeakNetUNetTPU(features=features, norm="batch", dtype=jnp.float32)
        x = jnp.asarray(rng.normal(size=(2, 16, 32, 1)).astype(np.float32))
        variables = _train_mode_stats(train_model, x)

        eval_model = PeakNetUNetTPU(features=features, norm="batch_eval", dtype=jnp.float32)
        ref = eval_model.apply(variables, x)

        folded = fold_batchnorm(variables)
        frozen_model = PeakNetUNetTPU(features=features, norm="frozen", dtype=jnp.float32)
        got = frozen_model.apply(folded, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-4, atol=1e-4)

    def test_folded_params_feed_fused_infer(self, rng):
        """The whole point: a trained-then-folded checkpoint must drive
        peaknet_tpu_fused_infer (interpret mode on CPU = same math as the
        TPU kernels)."""
        from psana_ray_tpu.models.pallas_unet import peaknet_tpu_fused_infer

        features = (8, 16, 16)
        train_model = PeakNetUNetTPU(features=features, norm="batch")
        x = jnp.asarray(rng.normal(size=(1, 32, 64, 1)).astype(np.float32))
        variables = _train_mode_stats(train_model, x)
        folded = fold_batchnorm(variables)

        frozen_model = PeakNetUNetTPU(features=features, norm="frozen")
        ref = np.asarray(frozen_model.apply(folded, x), np.float32)
        got = np.asarray(
            peaknet_tpu_fused_infer(folded, x, features=features, interpret=True),
            np.float32,
        )
        rel = np.max(np.abs(ref - got)) / max(np.max(np.abs(ref)), 1e-3)
        assert rel < 0.05  # bf16 kernel tolerance (same bar as test_pallas_unet)


class TestBatchNormTraining:
    def test_train_step_updates_stats_and_params(self):
        import optax

        from psana_ray_tpu.parallel import create_mesh
        from psana_ray_tpu.parallel.steps import create_train_state, make_train_step

        model = PeakNetUNetTPU(features=(8, 16), norm="batch")
        mesh = create_mesh(("data", "model"), (jax.device_count(), 1))
        opt = optax.adam(1e-3)
        x = jnp.ones((2, 16, 16, 1))
        state = create_train_state(model, opt, jax.random.key(0), x, mesh)
        assert "batch_stats" in state.variables

        def loss_fn(logits, _aux):
            return jnp.mean(logits**2)

        step = make_train_step(model, opt, loss_fn, donate=False)
        before_stats = jax.tree.map(np.asarray, state.variables["batch_stats"])
        before_params = jax.tree.map(np.asarray, state.variables["params"])
        xb = jnp.asarray(np.random.default_rng(0).normal(size=(2, 16, 16, 1)), jnp.float32)
        new_state, loss = step(state, xb, None)
        assert np.isfinite(float(loss))
        # running stats moved (mean update from a non-zero batch)...
        moved = jax.tree.map(
            lambda a, b: float(np.max(np.abs(np.asarray(a) - b))),
            new_state.variables["batch_stats"], before_stats,
        )
        assert max(jax.tree.leaves(moved)) > 0
        # ...and so did the params (gradients flowed to 'params' only)
        pmoved = jax.tree.map(
            lambda a, b: float(np.max(np.abs(np.asarray(a) - b))),
            new_state.variables["params"], before_params,
        )
        assert max(jax.tree.leaves(pmoved)) > 0

    def test_train_step_with_remat(self):
        import optax

        from psana_ray_tpu.parallel import create_mesh
        from psana_ray_tpu.parallel.steps import create_train_state, make_train_step

        model = PeakNetUNetTPU(features=(8, 16), norm="batch")
        mesh = create_mesh(("data", "model"), (jax.device_count(), 1))
        opt = optax.adam(1e-3)
        x = jnp.ones((2, 16, 16, 1))
        state = create_train_state(model, opt, jax.random.key(0), x, mesh)
        step = make_train_step(
            model, opt, lambda logits, _aux: jnp.mean(logits**2), donate=False,
            remat=True,
        )
        _, loss = step(state, x, None)
        assert np.isfinite(float(loss))


class TestExportRoundtrip:
    def test_export_serving_params_orbax_roundtrip(self, rng, tmp_path):
        from psana_ray_tpu.checkpoint import load_params
        from psana_ray_tpu.models import export_serving_params

        model = PeakNetUNetTPU(features=(8, 16), norm="batch", dtype=jnp.float32)
        x = jnp.asarray(rng.normal(size=(2, 16, 16, 1)).astype(np.float32))
        variables = _train_mode_stats(model, x)

        path = str(tmp_path / "serving")
        folded = export_serving_params(variables, path)
        restored = load_params(path)
        assert jax.tree_util.tree_structure(restored) == jax.tree_util.tree_structure(
            jax.tree.map(np.asarray, folded)
        )

        frozen = PeakNetUNetTPU(features=(8, 16), norm="frozen", dtype=jnp.float32)
        np.testing.assert_allclose(
            np.asarray(frozen.apply(restored, x)),
            np.asarray(frozen.apply(folded, x)),
            rtol=1e-6, atol=1e-6,
        )
