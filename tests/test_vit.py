"""ViT hit classifier: the sequence-parallel consumer (VERDICT r3 #4).

The SP equivalence bar: the ulysses-served model over a ('data', 'seq')
mesh must match the single-device flash model on identical params."""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psana_ray_tpu.models import ViTHitClassifier
from psana_ray_tpu.models.vit import patchify_panels
from psana_ray_tpu.parallel import create_mesh
from psana_ray_tpu.parallel.ring_attention import ulysses_attention


@pytest.fixture(scope="module")
def dp_sp_mesh():
    return create_mesh(("data", "seq"), (2, 4))


def _frames(rng, b=2, p=2, h=16, w=32):
    return jnp.asarray(rng.normal(size=(b, p, h, w)).astype(np.float32))


def _small_vit(attn_fn=None):
    return ViTHitClassifier(
        patch=8, embed_dim=64, depth=2, num_heads=4, num_classes=2,
        dtype=jnp.float32, attn_fn=attn_fn,
    )


class TestPatchify:
    def test_exact_relayout(self):
        frames = jnp.arange(2 * 1 * 4 * 4, dtype=jnp.float32).reshape(2, 1, 4, 4)
        toks = patchify_panels(frames, 2)
        assert toks.shape == (2, 4, 4)
        # token 0 of frame 0 = top-left 2x2 patch, row-major
        np.testing.assert_array_equal(np.asarray(toks[0, 0]), [0, 1, 4, 5])
        np.testing.assert_array_equal(np.asarray(toks[0, 3]), [10, 11, 14, 15])

    def test_panel_tokens_concatenate(self):
        rng = np.random.default_rng(0)
        frames = jnp.asarray(rng.normal(size=(1, 3, 8, 8)).astype(np.float32))
        toks = patchify_panels(frames, 4)
        assert toks.shape == (1, 3 * 4, 16)
        # panel 2's first token is the panel's own top-left patch
        np.testing.assert_array_equal(
            np.asarray(toks[0, 8]), np.asarray(frames[0, 2, :4, :4]).reshape(-1)
        )

    def test_rejects_misaligned(self):
        with pytest.raises(ValueError, match="divisible"):
            patchify_panels(jnp.zeros((1, 1, 10, 16)), 4)


class TestViTForward:
    def test_shapes_and_dtype(self, rng):
        model = _small_vit()
        x = _frames(rng)
        out = model.apply(model.init(jax.random.key(0), x), x)
        assert out.shape == (2, 2)
        assert out.dtype == jnp.float32
        assert np.isfinite(np.asarray(out)).all()

    def test_epix_geometry_token_count(self):
        # epix10k2M at patch 16: 16 panels x 22x24 = 8448 tokens, S % 128 == 0
        # (the flash kernel's sequence constraint on real geometry)
        model = ViTHitClassifier()
        shapes = jax.eval_shape(
            model.init, jax.random.key(0),
            jax.ShapeDtypeStruct((1, 16, 352, 384), jnp.float32),
        )
        pos = shapes["params"]["embed"]["pos_embed"]
        assert pos.shape == (1, 8448, 512)
        assert 8448 % 128 == 0

    def test_grads_flow(self, rng):
        model = _small_vit()
        x = _frames(rng)
        variables = model.init(jax.random.key(0), x)

        g = jax.grad(lambda v: jnp.sum(model.apply(v, x) ** 2))(variables)
        leaves = jax.tree.leaves(g)
        assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
        assert any(float(jnp.max(jnp.abs(l))) > 0 for l in leaves)


class TestViTSequenceParallel:
    def test_ulysses_served_matches_single_device(self, rng, dp_sp_mesh):
        """Same params, two attention paths: single-device flash vs
        ulysses all-to-all over ('data', 'seq') — outputs must agree."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        single = _small_vit()
        sp = _small_vit(
            attn_fn=functools.partial(
                ulysses_attention, mesh=dp_sp_mesh, seq_axis="seq",
                data_axis="data", impl="flash",
            )
        )
        x = _frames(rng)
        variables = single.init(jax.random.key(0), x)
        want = single.apply(variables, x)

        xs = jax.device_put(x, NamedSharding(dp_sp_mesh, P("data")))
        got = jax.jit(sp.apply)(variables, xs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)

    def test_ulysses_served_grads(self, rng, dp_sp_mesh):
        """The SP trunk must be trainable (ulysses flash VJP end to end)."""
        sp = _small_vit(
            attn_fn=functools.partial(
                ulysses_attention, mesh=dp_sp_mesh, seq_axis="seq",
                data_axis="data", impl="flash",
            )
        )
        x = _frames(rng)
        variables = sp.init(jax.random.key(0), x)
        g = jax.jit(jax.grad(lambda v: jnp.sum(sp.apply(v, x) ** 2)))(variables)
        leaves = jax.tree.leaves(g)
        assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
        assert any(float(jnp.max(jnp.abs(l))) > 0 for l in leaves)
