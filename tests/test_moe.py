"""Expert parallelism: switch-routing MoE with capacity-bounded dispatch.

The behavior bar for parallel/moe.py: routing semantics (top-1, FIFO
capacity, drop-to-residual), dense equivalence in the degenerate case,
the Switch load-balance loss, and sharded-vs-single-device agreement on a
('data', 'expert') mesh. The reference has no EP (SURVEY.md §2); these
tests define it."""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from flax.core import meta as nn_meta

from psana_ray_tpu.models import ViTHitClassifier
from psana_ray_tpu.models.losses import masked_softmax_xent
from psana_ray_tpu.parallel import SwitchMoEMlp, create_mesh, total_aux_loss
from psana_ray_tpu.parallel.steps import create_train_state, make_train_step


@pytest.fixture(scope="module")
def ep_mesh():
    return create_mesh(("data", "expert"), (2, 4))


def _moe(e=4, d=8, cap=2.0):
    return SwitchMoEMlp(
        embed_dim=d, num_experts=e, mlp_ratio=2, capacity_factor=cap,
        dtype=jnp.float32,
    )


class TestRouting:
    def test_single_expert_equals_gated_dense(self, rng):
        # E=1 with ample capacity: every token routes to expert 0 at
        # gate 1.0 (softmax over one logit), so the layer IS its FFN
        x = jnp.asarray(rng.normal(size=(2, 6, 8)).astype(np.float32))
        moe = _moe(e=1, cap=8.0)
        v = moe.init(jax.random.key(0), x)
        y = moe.apply(v, x)
        p = nn_meta.unbox(v)["params"]
        dense = (
            jax.nn.gelu(x @ p["w_up"][0] + p["b_up"][0]) @ p["w_dn"][0] + p["b_dn"][0]
        )
        np.testing.assert_allclose(np.asarray(y), np.asarray(dense), rtol=1e-5, atol=1e-6)

    def test_overflow_tokens_drop_to_zero(self, rng):
        # capacity 1 per expert, all tokens forced to one expert by a
        # biased router: only the FIRST token per batch row survives
        x = jnp.asarray(rng.normal(size=(1, 5, 8)).astype(np.float32))
        moe = _moe(e=4, cap=0.2)  # cap = ceil(5*0.2/4) = 1
        v = nn_meta.unbox(moe.init(jax.random.key(0), x))
        # bias the router hard toward expert 2
        v = jax.tree.map(lambda a: a, v)
        router_b = np.zeros((4,), np.float32)
        router_b[2] = 1e4
        v["params"]["router"]["bias"] = jnp.asarray(router_b)
        y = moe.apply(v, x)
        row_norms = np.linalg.norm(np.asarray(y[0]), axis=-1)
        assert row_norms[0] > 0  # token 0 won the single capacity slot
        np.testing.assert_allclose(row_norms[1:], 0.0, atol=1e-6)  # rest dropped

    def test_aux_loss_balanced_is_one(self, rng):
        # perfectly uniform routing makes E * sum(f*p) -> 1 (Switch eq. 4
        # lower bound); a hard-collapsed router scores ~E
        x = jnp.asarray(rng.normal(size=(2, 64, 8)).astype(np.float32))
        moe = _moe(e=4)
        v = nn_meta.unbox(moe.init(jax.random.key(0), x))
        _, inter = moe.apply(v, x, mutable=["intermediates"])
        balanced = float(total_aux_loss(inter["intermediates"]))
        assert 0.9 < balanced < 2.5  # near-uniform at random init

        router_b = np.zeros((4,), np.float32)
        router_b[1] = 1e4
        v["params"]["router"]["bias"] = jnp.asarray(router_b)
        _, inter = moe.apply(v, x, mutable=["intermediates"])
        collapsed = float(total_aux_loss(inter["intermediates"]))
        assert collapsed > 3.5  # ~E when all tokens hit one expert
        assert collapsed > balanced

    def test_aux_loss_ignores_other_sown_intermediates(self, rng):
        """Only leaves under an 'aux_loss' key count (ADVICE r4): a debug
        stat sown into the same collection must not change the total."""
        x = jnp.asarray(rng.normal(size=(2, 64, 8)).astype(np.float32))
        moe = _moe(e=4)
        v = nn_meta.unbox(moe.init(jax.random.key(0), x))
        _, inter = moe.apply(v, x, mutable=["intermediates"])
        want = float(total_aux_loss(inter["intermediates"]))
        polluted = dict(inter["intermediates"])
        polluted["debug_stat"] = (jnp.full((), 1e6, jnp.float32),)
        assert float(total_aux_loss(polluted)) == want

    def test_capacity_is_static(self):
        # same module, two token counts -> two capacities, no recompile
        # errors (capacity derives from shapes at trace time)
        moe = _moe(e=2, cap=1.0)
        x8 = jnp.zeros((1, 8, 8), jnp.float32)
        x16 = jnp.zeros((1, 16, 8), jnp.float32)
        v = moe.init(jax.random.key(0), x8)
        assert moe.apply(v, x8).shape == (1, 8, 8)
        assert moe.apply(v, x16).shape == (1, 16, 8)


class TestGroupedDispatch:
    """Token-axis chunking (VERDICT r4 weak #4): the dispatch tensor at
    detector scale must be [B·T/G, G, E, C_g], not the ~1.1 GB/layer
    monolithic [B, T, E, C]."""

    def test_pick_group_size(self):
        from psana_ray_tpu.parallel.moe import pick_group_size

        assert pick_group_size(8448, 512) == 384  # ViT serving shape
        assert pick_group_size(64, 512) == 64  # small seqs stay monolithic
        assert pick_group_size(1056, 512) == 352
        assert pick_group_size(8448, 512) * (8448 // 384) == 8448
        assert pick_group_size(7, 4) == 1  # prime beyond cap: degenerate

    def test_grouped_equals_monolithic_when_nothing_drops(self, rng):
        # with capacity_factor >= E no token can overflow in EITHER
        # grouping (worst case: a whole group on one expert), so grouped
        # and monolithic dispatch are numerically identical
        x = jnp.asarray(rng.normal(size=(2, 64, 8)).astype(np.float32))
        kw = dict(embed_dim=8, num_experts=4, mlp_ratio=2,
                  capacity_factor=4.0, dtype=jnp.float32)
        mono = SwitchMoEMlp(**kw, group_size=64)
        grouped = SwitchMoEMlp(**kw, group_size=16)
        v = mono.init(jax.random.key(0), x)
        np.testing.assert_allclose(
            np.asarray(mono.apply(v, x)),
            np.asarray(grouped.apply(v, x)),
            rtol=1e-5, atol=1e-6,
        )

    def test_group_must_divide_tokens(self, rng):
        x = jnp.zeros((1, 10, 8), jnp.float32)
        moe = SwitchMoEMlp(embed_dim=8, num_experts=2, group_size=4,
                           dtype=jnp.float32)
        with pytest.raises(ValueError, match="does not divide"):
            moe.init(jax.random.key(0), x)

    def test_grouped_dispatch_tensor_is_bounded(self):
        # trace-level proof for the serving scale: no intermediate in the
        # jaxpr may reach the monolithic dispatch size (T*E*C elements).
        # T=8448, E=4, cf=2: monolithic C=4224 -> 285M elems at B=1;
        # grouped G=384, C_g=192 -> the largest dispatch-shaped tensor is
        # 8448*4*192 = 6.5M elems per batch row
        t, e, d = 8448, 4, 64
        moe = SwitchMoEMlp(embed_dim=d, num_experts=e, mlp_ratio=2,
                           capacity_factor=2.0, dtype=jnp.bfloat16)
        x = jax.ShapeDtypeStruct((1, t, d), jnp.bfloat16)
        v = jax.eval_shape(
            lambda: moe.init(jax.random.key(0), jnp.zeros((1, 64, d), jnp.bfloat16))
        )
        jaxpr = jax.make_jaxpr(
            lambda vv, xx: moe.apply(vv, xx), static_argnums=()
        )(v, x)
        monolithic = t * e * math.ceil(t * 2.0 / e)
        biggest = max(
            int(np.prod(eqn_var.aval.shape))
            for eqn in jaxpr.eqns
            for eqn_var in eqn.outvars
            if hasattr(eqn_var.aval, "shape")
        )
        assert biggest < monolithic / 10, (
            f"largest traced intermediate {biggest} elems — grouping not "
            f"effective (monolithic dispatch would be {monolithic})"
        )

    def test_sharded_matches_single_device_at_1k_tokens(self, rng, ep_mesh):
        # VERDICT r4 do #5: the sharded==single assertion at >=1k tokens,
        # where grouping is active (auto G=352 for T=1056)
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = jnp.asarray(rng.normal(size=(2, 1056, 8)).astype(np.float32))
        moe = _moe(e=4, cap=2.0)
        v = nn_meta.unbox(moe.init(jax.random.key(0), x))
        want = moe.apply(v, x)
        xs = jax.device_put(x, NamedSharding(ep_mesh, P("data")))
        got = jax.jit(moe.apply)(v, xs)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5
        )


class TestExpertParallel:
    def test_sharded_matches_single_device(self, rng, ep_mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P

        model = ViTHitClassifier(
            patch=8, embed_dim=64, depth=2, num_heads=4, num_classes=2,
            dtype=jnp.float32, moe_experts=4,
        )
        frames = jnp.asarray(rng.normal(size=(4, 2, 16, 32)).astype(np.float32))
        variables = model.init(jax.random.key(0), frames)
        want = model.apply(variables, frames)

        unboxed = nn_meta.unbox(variables)
        xs = jax.device_put(frames, NamedSharding(ep_mesh, P("data")))
        got = jax.jit(model.apply)(unboxed, xs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5)

    def test_expert_weights_shard_on_expert_axis(self, rng, ep_mesh):
        # init_sharded (via create_train_state) places w_up/w_dn on the
        # expert axis — each device holds E/4 experts, not all of them
        model = ViTHitClassifier(
            patch=8, embed_dim=64, depth=2, num_heads=4, num_classes=2,
            dtype=jnp.float32, moe_experts=4, scan_trunk=True,
        )
        frames = jnp.asarray(rng.normal(size=(8, 2, 16, 32)).astype(np.float32))
        state = create_train_state(
            model, optax.adamw(1e-3), jax.random.key(1), frames, ep_mesh
        )
        w_up = state.variables["params"]["trunk"]["blocks"]["block"]["moe"]["w_up"]
        # scanned trunk: [layers, expert, d, f]; expert axis sharded
        assert w_up.shape[:2] == (2, 4)
        spec = w_up.sharding.spec
        assert spec[1] == "expert", spec

    def test_moe_vit_train_step_with_aux_loss(self, rng, ep_mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P

        model = ViTHitClassifier(
            patch=8, embed_dim=64, depth=2, num_heads=4, num_classes=2,
            dtype=jnp.float32, moe_experts=4, scan_trunk=True,
        )
        frames = jnp.asarray(rng.normal(size=(8, 2, 16, 32)).astype(np.float32))
        state = create_train_state(
            model, optax.adamw(1e-3), jax.random.key(1), frames, ep_mesh
        )
        step = make_train_step(
            model, optax.adamw(1e-3),
            lambda lg, aux: masked_softmax_xent(lg, aux[0], aux[1]),
            aux_loss_weight=0.01,
        )
        xs = jax.device_put(frames, NamedSharding(ep_mesh, P("data")))
        labels = jnp.asarray(np.arange(8) % 2)
        valid = jnp.ones((8,), jnp.uint8)
        state, loss = step(state, xs, (labels, valid))
        assert np.isfinite(float(loss))
        assert int(jax.device_get(state.step)) == 1
        # intermediates were consumed by the step, not folded into state
        assert "intermediates" not in state.variables

    def test_degrades_to_replication_without_expert_axis(self, rng):
        # the same MoE model must still initialize on a mesh with no
        # 'expert' axis (weights replicate) — rules degrade, not raise
        mesh = create_mesh(("data", "model"), (4, 2))
        model = ViTHitClassifier(
            patch=8, embed_dim=64, depth=2, num_heads=4, num_classes=2,
            dtype=jnp.float32, moe_experts=2,
        )
        frames = jnp.asarray(rng.normal(size=(8, 2, 16, 32)).astype(np.float32))
        state = create_train_state(
            model, optax.adamw(1e-3), jax.random.key(0), frames, mesh
        )
        w_up = jax.tree.leaves(
            {k: v for k, v in state.variables["params"].items()}
        )
        assert all(np.isfinite(np.asarray(jax.device_get(l))).all() for l in w_up)


def test_serving_capacity_factor_is_trace_time_only():
    """The serving-side capacity trick (bench: train at cf=2.0, serve at
    cf=1.25 for ~10% fps): expert capacity is a trace-time constant, so
    one trained tree must apply unchanged under ANY capacity factor, and
    with capacity >= tokens/expert-worst-case the outputs must agree
    exactly (no token ever dropped at either setting)."""
    rng = np.random.default_rng(3)
    kw = dict(patch=8, embed_dim=64, depth=2, num_heads=4, num_classes=2,
              dtype=jnp.float32, moe_experts=2)
    train_model = ViTHitClassifier(moe_capacity_factor=2.0, **kw)
    frames = jnp.asarray(rng.normal(size=(2, 2, 16, 32)).astype(np.float32))
    variables = nn_meta.unbox(train_model.init(jax.random.key(0), frames))

    # two NO-DROP capacities (cap=t vs cap=2t — cf=E and cf=2E): different
    # dispatch-tensor shapes, same routing outcome, so outputs must agree
    # exactly — proves capacity changes only the trace, and the padded
    # capacity slots' garbage never leaks into the combine. With E=2 the
    # first config equals train_model's cf=2.0, so it doubles as the
    # train-setting output
    e = float(kw["moe_experts"])
    out_nd1 = train_model.apply(variables, frames)  # cf=2.0 == cf=E here
    out_nd2 = ViTHitClassifier(moe_capacity_factor=2 * e, **kw).apply(variables, frames)
    np.testing.assert_allclose(
        np.asarray(out_nd1), np.asarray(out_nd2), rtol=1e-5, atol=1e-5
    )
    # the shipped train/serve settings: the cf=2.0 tree applies unchanged
    # at cf=1.25, right shape, finite (drops fall back to the residual)
    serve = ViTHitClassifier(moe_capacity_factor=1.25, **kw)
    out_lo = serve.apply(variables, frames)
    assert out_lo.shape == out_nd1.shape
    assert np.isfinite(np.asarray(out_lo)).all()
