"""Pipeline parallelism: GPipe microbatch schedule over a 'pipe' mesh axis.

The correctness bar for parallel/pp.py: the pipelined computation must
equal the sequential stage composition exactly (same params), in both
directions — forward outputs AND gradients — because the backward
schedule is derived by jax.grad through the ppermute ring, not written by
hand. The reference has no PP at all (SURVEY.md §2); these tests define
the behavior."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psana_ray_tpu.models import ViTHitClassifier, vit_pipelined_apply
from psana_ray_tpu.parallel import create_mesh, pipeline_apply, stack_stages


@pytest.fixture(scope="module")
def pipe_mesh():
    return create_mesh(("pipe",), (4,), devices=jax.devices()[:4])


@pytest.fixture(scope="module")
def dp_pp_mesh():
    return create_mesh(("data", "pipe"), (2, 4))


def _mlp_stage(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stacked_mlp(rng, n_stages, d):
    return {
        "w": jnp.asarray(rng.normal(0, 0.5, (n_stages, d, d)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(0, 0.1, (n_stages, d)).astype(np.float32)),
    }


def _sequential(stacked, x, n_stages):
    for i in range(n_stages):
        x = _mlp_stage(jax.tree.map(lambda p: p[i], stacked), x)
    return x


class TestPipelineApply:
    def test_matches_sequential(self, rng, pipe_mesh):
        stacked = _stacked_mlp(rng, 4, 8)
        x = jnp.asarray(rng.normal(size=(8, 8)).astype(np.float32))
        want = _sequential(stacked, x, 4)
        got = jax.jit(
            lambda p, x: pipeline_apply(_mlp_stage, p, x, pipe_mesh)
        )(stacked, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)

    def test_more_microbatches_shrink_nothing(self, rng, pipe_mesh):
        # M > S changes the schedule (smaller bubble), never the result
        stacked = _stacked_mlp(rng, 4, 8)
        x = jnp.asarray(rng.normal(size=(8, 8)).astype(np.float32))
        want = _sequential(stacked, x, 4)
        got = pipeline_apply(_mlp_stage, stacked, x, pipe_mesh, microbatches=8)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)

    def test_gradients_match_sequential(self, rng, pipe_mesh):
        # jax.grad through the ring = the reverse pipeline schedule;
        # param AND input cotangents must match the sequential program
        stacked = _stacked_mlp(rng, 4, 8)
        x = jnp.asarray(rng.normal(size=(8, 8)).astype(np.float32))

        gp_pp, gx_pp = jax.jit(
            jax.grad(
                lambda p, x: jnp.sum(pipeline_apply(_mlp_stage, p, x, pipe_mesh) ** 2),
                argnums=(0, 1),
            )
        )(stacked, x)
        gp_sq, gx_sq = jax.grad(
            lambda p, x: jnp.sum(_sequential(p, x, 4) ** 2), argnums=(0, 1)
        )(stacked, x)
        for a, b in zip(jax.tree.leaves(gp_pp), jax.tree.leaves(gp_sq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(gx_pp), np.asarray(gx_sq), rtol=1e-5, atol=1e-6)

    def test_dp_pp_compose(self, rng, dp_pp_mesh):
        # batch rows sharded over 'data', stages over 'pipe': each data
        # group runs an independent pipeline, result is the same function
        from jax.sharding import NamedSharding, PartitionSpec as P

        stacked = _stacked_mlp(rng, 4, 8)
        x = jnp.asarray(rng.normal(size=(8, 8)).astype(np.float32))
        want = _sequential(stacked, x, 4)
        xs = jax.device_put(x, NamedSharding(dp_pp_mesh, P("data")))
        got = jax.jit(
            lambda p, x: pipeline_apply(_mlp_stage, p, x, dp_pp_mesh, data_axis="data")
        )(stacked, xs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)

    def test_rejects_indivisible_microbatches(self, rng, pipe_mesh):
        stacked = _stacked_mlp(rng, 4, 8)
        x = jnp.zeros((6, 8), jnp.float32)
        with pytest.raises(ValueError, match="not divisible"):
            pipeline_apply(_mlp_stage, stacked, x, pipe_mesh, microbatches=4)

    def test_stack_stages_regroups(self):
        depth = {"k": jnp.arange(8.0).reshape(8, 1)}
        staged = stack_stages(depth, 4)
        assert staged["k"].shape == (4, 2, 1)
        np.testing.assert_array_equal(np.asarray(staged["k"][1, 0]), [2.0])
        with pytest.raises(ValueError, match="not divisible"):
            stack_stages(depth, 3)


class TestViTPipelined:
    """The flagship consumer under PP: scan-trunk ViT, trunk as 4 GPipe
    stages of depth/4 blocks each."""

    def _vit(self, scan):
        return ViTHitClassifier(
            patch=8, embed_dim=64, depth=4, num_heads=4, num_classes=2,
            dtype=jnp.float32, scan_trunk=scan,
        )

    def test_scan_trunk_equals_loop_trunk(self, rng):
        # same math, different param layout: stacking the loop trunk's
        # block params must reproduce the scanned trunk bit-for-bit
        loop, scan = self._vit(False), self._vit(True)
        frames = jnp.asarray(rng.normal(size=(2, 2, 16, 32)).astype(np.float32))
        vl = loop.init(jax.random.key(0), frames)
        trunk = vl["params"]["trunk"]
        stacked = jax.tree.map(
            lambda *xs: jnp.stack(xs), *[trunk[f"block{i}"] for i in range(4)]
        )
        vs = {"params": {**vl["params"], "trunk": {"blocks": {"block": stacked}}}}
        np.testing.assert_allclose(
            np.asarray(loop.apply(vl, frames)),
            np.asarray(scan.apply(vs, frames)),
            rtol=1e-5, atol=1e-6,
        )

    def test_pipelined_matches_plain(self, rng, dp_pp_mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P

        model = self._vit(True)
        frames = jnp.asarray(rng.normal(size=(8, 2, 16, 32)).astype(np.float32))
        variables = model.init(jax.random.key(0), frames)
        want = model.apply(variables, frames)
        xs = jax.device_put(frames, NamedSharding(dp_pp_mesh, P("data")))
        got = jax.jit(
            lambda v, x: vit_pipelined_apply(model, v, x, dp_pp_mesh, data_axis="data")
        )(variables, xs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5)

    def test_pipelined_trains(self, rng, dp_pp_mesh):
        # grads of the pipelined ViT == grads of the plain apply
        model = self._vit(True)
        frames = jnp.asarray(rng.normal(size=(8, 2, 16, 32)).astype(np.float32))
        variables = model.init(jax.random.key(0), frames)

        g_pp = jax.jit(
            jax.grad(
                lambda v: jnp.sum(
                    vit_pipelined_apply(model, v, frames, dp_pp_mesh, data_axis="data") ** 2
                )
            )
        )(variables)
        g_plain = jax.jit(jax.grad(lambda v: jnp.sum(model.apply(v, frames) ** 2)))(
            variables
        )
        for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_plain)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
            )

    def test_requires_scan_trunk(self, rng, dp_pp_mesh):
        model = self._vit(False)
        frames = jnp.zeros((8, 2, 16, 32), jnp.float32)
        variables = model.init(jax.random.key(0), frames)
        with pytest.raises(ValueError, match="scan_trunk"):
            vit_pipelined_apply(model, variables, frames, dp_pp_mesh)

    def _moe_vit(self):
        return ViTHitClassifier(
            patch=8, embed_dim=64, depth=4, num_heads=4, num_classes=2,
            dtype=jnp.float32, scan_trunk=True, moe_experts=2,
        )

    def test_moe_training_raises_serving_works(self, rng, dp_pp_mesh):
        """PP×EP training silently drops the router's load-balance loss
        (VERDICT r4 weak #5): differentiating through vit_pipelined_apply
        with moe_experts>0 must raise; serving (no grad) stays exact."""
        from flax.core import meta as nn_meta

        model = self._moe_vit()
        frames = jnp.asarray(rng.normal(size=(8, 2, 16, 32)).astype(np.float32))
        variables = nn_meta.unbox(model.init(jax.random.key(0), frames))

        # serving: unaffected, matches plain apply
        want = model.apply(variables, frames)
        got = vit_pipelined_apply(model, variables, frames, dp_pp_mesh,
                                  data_axis="data")
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5
        )

        def loss(v):
            return jnp.sum(
                vit_pipelined_apply(model, v, frames, dp_pp_mesh,
                                    data_axis="data") ** 2
            )

        with pytest.raises(ValueError, match="load-balancing aux loss"):
            jax.grad(loss)(variables)
        with pytest.raises(ValueError, match="load-balancing aux loss"):
            jax.jit(jax.grad(loss))(variables)  # jit-of-grad
        with pytest.raises(ValueError, match="load-balancing aux loss"):
            # grad-of-jit: the Python body is gone by the time AD runs on
            # the extracted jaxpr — only the custom-vjp guard catches this
            jax.grad(jax.jit(loss))(variables)

    def test_moe_training_explicit_override(self, rng, dp_pp_mesh):
        """allow_unbalanced_moe=True accepts the trade explicitly and the
        gradient flows (matching plain-apply grads, which also see no aux
        loss when only 'params' is bound)."""
        model = self._moe_vit()
        frames = jnp.asarray(rng.normal(size=(8, 2, 16, 32)).astype(np.float32))
        from flax.core import meta as nn_meta

        variables = nn_meta.unbox(model.init(jax.random.key(0), frames))

        g_pp = jax.grad(
            lambda v: jnp.sum(
                vit_pipelined_apply(model, v, frames, dp_pp_mesh,
                                    data_axis="data",
                                    allow_unbalanced_moe=True) ** 2
            )
        )(variables)
        g_plain = jax.grad(
            lambda v: jnp.sum(model.apply(v, frames) ** 2)
        )(variables)
        for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_plain)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
            )
