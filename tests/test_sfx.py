"""The assembled SFX capability, end to end: stream -> PeakNet -> CXI.

The reference's packaging names this as the mission ("Save PeakNet
inference results to CXI", reference ``setup.py:11``) but ships no code
for it; these tests define the behavior for psana_ray_tpu.sfx. The e2e
test is an ORACLE test: synthetic events carry planted peak ground truth,
a small PeakNet trains briefly on the self-supervised label recipe, and
the CXI file written by the pipeline must recover the planted peaks
within tolerance — proving the whole chain (transport, batcher, jitted
segmentation+extraction, panel->raw coordinate fold, HDF5 layout,
cursor) preserves the physics, not just the plumbing."""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DET = "smoke_a"
SEED = 5
FEATURES = (8, 16)
EVAL_RUN = 2  # training uses run=1 events 0..319; run 2 reseeds every event
N_EVENTS = 12


def _train_and_export(out_dir: str):
    """The documented train->serve recipe (examples/train_peaknet.py at
    smoke scale): 80 steps of focal-loss training on self-derived labels
    (calibrated intensity > 50), norm='batch', then the exact
    export_serving_params fold. Measured on this recipe: recall ~0.73,
    precision ~0.99 against planted truth at threshold 0.5 / min_dist 2."""
    import optax
    from flax.core import meta

    from psana_ray_tpu.models import (
        PeakNetUNetTPU,
        export_serving_params,
        host_init,
        panels_to_nhwc,
    )
    from psana_ray_tpu.models.losses import masked_sigmoid_focal
    from psana_ray_tpu.parallel.steps import TrainState, make_train_step
    from psana_ray_tpu.sources import SyntheticSource

    src = SyntheticSource(num_events=1, detector_name=DET, seed=SEED)
    p, h, w = src.spec.frame_shape
    b, n_steps = 4, 80
    model = PeakNetUNetTPU(features=FEATURES, norm="batch", s2d=2)
    variables = meta.unbox(host_init(model, (b * p, h, w, 1)))
    opt = optax.adam(3e-3)
    opt_state = jax.jit(opt.init)({"params": variables["params"]})
    state = TrainState(variables, opt_state, jnp.zeros((), jnp.int32))
    step = make_train_step(
        model, opt,
        lambda lg, aux: masked_sigmoid_focal(lg, aux[0], aux[1], alpha=0.9),
    )

    @jax.jit
    def prepare(frames):
        x = panels_to_nhwc(frames, mode="batch")
        return x, (x > 50.0).astype(jnp.float32)

    for s in range(n_steps):
        frames = np.stack([src.event(s * b + j)[0] for j in range(b)])
        x, tg = prepare(jnp.asarray(frames))
        state, _ = step(state, x, (tg, jnp.ones((b * p,), jnp.uint8)))
    export_serving_params(state.variables, out_dir)


@pytest.fixture(scope="module")
def serving_ckpt(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("sfx") / "serving")
    _train_and_export(d)
    return d


def _truth_raw_coords(idx: int, panel_h: int) -> np.ndarray:
    """Planted truth for event ``idx`` in the pipeline's unassembled raw
    layout: rows (0, y_raw, x_raw, amplitude)."""
    from psana_ray_tpu.sources import SyntheticSource

    src = SyntheticSource(run=EVAL_RUN, num_events=1, detector_name=DET, seed=SEED)
    _, _, truth = src.event_with_truth(idx)
    t = truth.copy()
    t[:, 1] = t[:, 0] * panel_h + t[:, 1]  # y_raw = panel*H + cy
    t[:, 0] = 0
    return t


def _score_cxi(path: str, panel_h: int):
    """Greedy-match every CXI event's peaks against its planted truth."""
    from psana_ray_tpu.models.peaks import peak_metrics, read_cxi_peaks

    n, x, y, inten, event_idx = read_cxi_peaks(path)
    pred_yx = np.stack([y, x], axis=-1)
    truth = [_truth_raw_coords(int(e), panel_h) for e in event_idx]
    return peak_metrics(pred_yx, n, truth, tolerance=3.0, min_amplitude=100.0), set(
        int(e) for e in event_idx
    )


def test_infer_s2d_reads_checkpoint(serving_ckpt):
    from psana_ray_tpu.checkpoint import load_params
    from psana_ray_tpu.sfx import infer_s2d

    v = load_params(serving_ckpt)
    assert infer_s2d(v.get("params", v)) == 2
    with pytest.raises(ValueError, match="logits"):
        infer_s2d({"not": "a tree"})


def test_infer_features_reads_checkpoint(serving_ckpt):
    from psana_ray_tpu.checkpoint import load_params
    from psana_ray_tpu.sfx import infer_features

    v = load_params(serving_ckpt)
    assert infer_features(v.get("params", v)) == FEATURES
    with pytest.raises(ValueError, match="ConvBlock_0"):
        infer_features({"not": "a tree"})


def test_features_mismatch_refused(serving_ckpt, tmp_path):
    """An explicit features tuple that contradicts the checkpoint is an
    early clear refusal, not a shape error deep in the first apply."""
    from psana_ray_tpu.checkpoint import load_params
    from psana_ray_tpu.cxi import CxiWriter
    from psana_ray_tpu.sfx import SfxPipeline

    with CxiWriter(str(tmp_path / "x.cxi")) as w:
        with pytest.raises(ValueError, match="does not match the checkpoint"):
            SfxPipeline(load_params(serving_ckpt), w, features=(4, 8))


def test_e2e_stream_to_cxi_recovers_planted_peaks(serving_ckpt, tmp_path):
    """The full library-surface pipeline: ProducerRuntime streaming
    held-out synthetic events -> queue -> SfxPipeline -> CXI file whose
    peak lists match the planted ground truth; cursor advances to the
    stream's end."""
    from psana_ray_tpu.checkpoint import StreamCursor, load_params
    from psana_ray_tpu.config import PipelineConfig, SourceConfig
    from psana_ray_tpu.models.peaks import CxiWriter
    from psana_ray_tpu.producer import ProducerRuntime
    from psana_ray_tpu.sfx import SfxConfig, SfxPipeline
    from psana_ray_tpu.sources.base import DETECTORS
    from psana_ray_tpu.transport.addressing import open_queue

    cfg = PipelineConfig(
        source=SourceConfig(
            exp="synthetic", run=EVAL_RUN, num_events=N_EVENTS,
            detector_name=DET, seed=SEED,
        )
    )
    ProducerRuntime(cfg).run(block=False)
    queue = open_queue(cfg.transport)

    cxi = str(tmp_path / "run.cxi")
    cursor_path = str(tmp_path / "run.cursor")
    cursor = StreamCursor(stride=1)
    variables = load_params(serving_ckpt)
    with CxiWriter(cxi, max_peaks=64) as writer:
        pipe = SfxPipeline(
            variables, writer, features=FEATURES,
            config=SfxConfig(batch_size=4),
        )
        n = pipe.run(queue, cursor=cursor, cursor_path=cursor_path)
    assert n == N_EVENTS
    assert pipe.n_peaks > 0

    h = DETECTORS[DET].height
    m, events = _score_cxi(cxi, h)
    assert events == set(range(N_EVENTS))
    # the physics bar: planted peaks recovered through the WHOLE pipeline
    assert m["recall"] >= 0.6, m
    assert m["precision"] >= 0.8, m

    # resume watermark is durable and complete
    resumed = StreamCursor.load(cursor_path)
    assert resumed.resume_point(0) == N_EVENTS


def test_competing_sfx_consumers_partition_and_merge(serving_ckpt, tmp_path):
    """The pod deployment shape: TWO SfxPipeline consumers compete on ONE
    queue (the reference's consumer-side DP, SURVEY §2 row 22), each
    writing its own CXI file; the dynamic partition must be disjoint and
    exhaustive, both consumers must terminate on the shared EOS (the
    batcher re-enqueues sibling markers), and `merge_cxi` must reassemble
    the full run from the per-consumer files."""
    from psana_ray_tpu.checkpoint import load_params
    from psana_ray_tpu.config import PipelineConfig, SourceConfig, TransportConfig
    from psana_ray_tpu.cxi import merge_cxi, read_cxi_peaks
    from psana_ray_tpu.models.peaks import CxiWriter
    from psana_ray_tpu.producer import ProducerRuntime
    from psana_ray_tpu.sfx import SfxConfig, SfxPipeline
    from psana_ray_tpu.transport.addressing import open_queue

    cfg = PipelineConfig(
        source=SourceConfig(
            exp="synthetic", run=EVAL_RUN, num_events=N_EVENTS,
            detector_name=DET, seed=SEED,
        ),
        # one EOS marker per expected consumer (reference parity,
        # producer.py:124-125) — without this the first consumer to pop
        # the single marker ends the stream and its sibling waits forever
        transport=TransportConfig(num_consumers=2),
    )
    ProducerRuntime(cfg).run(block=False)
    variables = load_params(serving_ckpt)
    paths = [str(tmp_path / f"consumer{i}.cxi") for i in range(2)]
    counts = [None, None]
    errors = []

    def consume(i):
        try:
            queue = open_queue(cfg.transport)
            with CxiWriter(paths[i], max_peaks=64) as writer:
                pipe = SfxPipeline(
                    variables, writer, features=FEATURES,
                    config=SfxConfig(batch_size=2),
                )
                counts[i] = pipe.run(queue)
        except BaseException as e:  # surfaced in the main thread
            errors.append((i, e))

    # daemon: if EOS fan-out regresses, a consumer blocks forever in
    # batches_from_queue — the join-timeout assertion must then fail the
    # test rather than the stuck non-daemon thread hanging pytest exit
    threads = [
        threading.Thread(target=consume, args=(i,), daemon=True) for i in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
        assert not t.is_alive(), "competing consumer failed to terminate on EOS"
    assert not errors, errors

    per_consumer = [set(int(e) for e in read_cxi_peaks(p)[4]) for p in paths]
    assert per_consumer[0] & per_consumer[1] == set(), "duplicate delivery"
    assert per_consumer[0] | per_consumer[1] == set(range(N_EVENTS))
    assert sum(counts) == N_EVENTS

    merged = str(tmp_path / "merged.cxi")
    assert merge_cxi(paths, merged) == N_EVENTS
    n, *_rest, event_idx = read_cxi_peaks(merged)
    assert len(n) == N_EVENTS
    assert [int(e) for e in event_idx] == list(range(N_EVENTS))


@pytest.mark.slow
def test_sfx_cli_subprocess_over_shm(serving_ckpt, tmp_path):
    """The installed-CLI surface: a real `python -m psana_ray_tpu.sfx`
    process drains an shm ring fed by this process and writes the CXI
    file — the runbook's operator path."""
    from psana_ray_tpu.records import EndOfStream, FrameRecord
    from psana_ray_tpu.sources import SyntheticSource
    from psana_ray_tpu.transport.shm_ring import ShmRingBuffer, native_available

    if not native_available():
        pytest.skip("native shm ring unavailable")

    name = f"sfx_test_{os.getpid()}"
    cxi = str(tmp_path / "cli.cxi")
    src = SyntheticSource(
        run=EVAL_RUN, num_events=8, detector_name=DET, seed=SEED,
    )
    frame_bytes = int(np.prod(src.spec.frame_shape)) * 4
    ring = ShmRingBuffer.create(name, maxsize=16, slot_bytes=frame_bytes + 4096)
    try:
        def produce():
            for idx, data, energy in src.iter_indexed_events():
                while not ring.put(FrameRecord(0, idx, data, energy)):
                    time.sleep(0.002)
            assert ring.put_wait(EndOfStream(total_events=8), timeout=60.0)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        out = subprocess.run(
            [
                sys.executable, "-m", "psana_ray_tpu.sfx",
                "--address", f"shm://{name}",
                "--serving_params", serving_ckpt,
                "--features", ",".join(str(f) for f in FEATURES),
                "--mode", "quality",
                "--output", cxi,
                "--batch", "4",
            ],
            capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
        )
        t.join(timeout=60)
        assert out.returncode == 0, out.stderr[-2000:]
        from psana_ray_tpu.models.peaks import read_cxi_peaks

        n, *_ , event_idx = read_cxi_peaks(cxi)
        assert len(n) == 8
        assert set(int(e) for e in event_idx) == set(range(8))
    finally:
        ring.destroy()


def test_max_events_bound_drains_in_flight_batch(serving_ckpt, tmp_path):
    """--max_events stops the run near the bound; the one-deep pipelined
    loop may overshoot by at most one extra batch (which MUST still be
    written — it was dispatched, and the producer will not re-send it),
    and every written event is covered by the saved cursor."""
    from psana_ray_tpu.checkpoint import StreamCursor, load_params
    from psana_ray_tpu.config import PipelineConfig, SourceConfig
    from psana_ray_tpu.models.peaks import CxiWriter, read_cxi_peaks
    from psana_ray_tpu.producer import ProducerRuntime
    from psana_ray_tpu.sfx import SfxConfig, SfxPipeline
    from psana_ray_tpu.transport.addressing import open_queue

    cfg = PipelineConfig(
        source=SourceConfig(
            exp="synthetic", run=EVAL_RUN, num_events=N_EVENTS,
            detector_name=DET, seed=SEED,
        )
    )
    ProducerRuntime(cfg).run(block=False)
    queue = open_queue(cfg.transport)
    cxi = str(tmp_path / "bounded.cxi")
    cursor_path = str(tmp_path / "bounded.cursor")
    cursor = StreamCursor(stride=1)
    with CxiWriter(cxi, max_peaks=32) as writer:
        pipe = SfxPipeline(
            load_params(serving_ckpt), writer, features=FEATURES,
            config=SfxConfig(batch_size=2),
        )
        n = pipe.run(
            queue, cursor=cursor, cursor_path=cursor_path, max_events=5,
        )
    # bound reached, overshoot bounded by batch granularity + one in flight
    assert 5 <= n <= 5 + 2 * 2 - 1
    n_rows, *_ , event_idx = read_cxi_peaks(cxi)
    assert len(n_rows) == n
    # the durable watermark covers exactly what was written (contiguous
    # prefix: single shard, in-order stream)
    assert StreamCursor.load(cursor_path).resume_point(0) == n
    if hasattr(queue, "close"):
        queue.close()


def test_cxi_writer_append_mode(tmp_path):
    """Crash-resume must never truncate durably-written events: mode='a'
    re-opens and appends after the last event; a max_peaks mismatch (row
    width baked into the file) is refused."""
    from psana_ray_tpu.models.peaks import CxiWriter, PeakSet, read_cxi_peaks

    path = str(tmp_path / "resume.cxi")
    mk = lambda i: PeakSet(  # noqa: E731
        event_idx=i, shard_rank=0,
        y=np.array([1.0 * i]), x=np.array([2.0 * i]),
        intensity=np.array([0.9]), photon_energy=9.5,
    )
    with CxiWriter(path, max_peaks=16) as w:
        w.append([mk(0), mk(1), mk(2)])
    with CxiWriter(path, max_peaks=16, mode="a") as w:
        assert w.n_events == 3  # picked up where the crashed run stopped
        w.append([mk(3), mk(4)])
    n, x, y, inten, event_idx = read_cxi_peaks(path)
    assert list(event_idx) == [0, 1, 2, 3, 4]
    assert y[3][0] == 3.0  # pre-crash rows intact, post-resume rows real
    with pytest.raises(ValueError, match="max_peaks"):
        CxiWriter(path, max_peaks=32, mode="a")


def test_fresh_run_refuses_existing_output(serving_ckpt, tmp_path):
    """A fresh (non-resume) CLI run must not silently truncate an
    existing CXI file."""
    from psana_ray_tpu.sfx import main

    out = tmp_path / "exists.cxi"
    out.write_bytes(b"not empty")
    rc = main([
        "--serving_params", serving_ckpt,
        "--output", str(out),
    ])
    assert rc == 1
    assert out.read_bytes() == b"not empty"  # untouched


def test_merge_cxi_dedupes_at_least_once_replays(tmp_path):
    """The resume companion: merging a crashed run's file with its
    resumed run's file drops (shard_rank, event_idx) duplicates, keeping
    the resumed run's version, sorted deterministically."""
    from psana_ray_tpu.models.peaks import (
        CxiWriter,
        PeakSet,
        merge_cxi,
        read_cxi_peaksets,
    )

    mk = lambda i, v: PeakSet(  # noqa: E731
        event_idx=i, shard_rank=0,
        y=np.array([v], np.float32), x=np.array([v], np.float32),
        intensity=np.array([0.5], np.float32), photon_energy=9.0,
    )
    run1, run2 = str(tmp_path / "r1.cxi"), str(tmp_path / "r2.cxi")
    with CxiWriter(run1, max_peaks=8) as w:
        w.append([mk(0, 10.0), mk(1, 11.0), mk(2, 12.0)])
    with CxiWriter(run2, max_peaks=8) as w:  # resume re-processed 2, added 3-4
        w.append([mk(2, 99.0), mk(3, 13.0), mk(4, 14.0)])

    out = str(tmp_path / "merged.cxi")
    n = merge_cxi([run1, run2], out)  # max_peaks derived from inputs
    assert n == 5
    sets = read_cxi_peaksets(out)
    assert [p.event_idx for p in sets] == [0, 1, 2, 3, 4]
    assert sets[2].y[0] == 99.0  # resumed run superseded the crashed one
    assert sets[0].photon_energy == pytest.approx(9.0)  # keV round trip

    # no-clobber: an existing output is refused, never truncated
    with pytest.raises(ValueError, match="refusing to overwrite"):
        merge_cxi([run1, run2], out)
    # lossless: a narrower explicit max_peaks is refused, not truncated
    with pytest.raises(ValueError, match="lossless"):
        merge_cxi([run1, run2], str(tmp_path / "narrow.cxi"), max_peaks=4)

    out2 = str(tmp_path / "merged_first.cxi")
    merge_cxi([run1, run2], out2, keep="first")
    assert read_cxi_peaksets(out2)[2].y[0] == 12.0  # first kept instead


def test_merge_cxi_streaming_chunks_and_bad_inputs(tmp_path):
    """chunk_events smaller than the event count must not change the
    result (the two-pass streaming path); a missing input path and a
    foreign HDF5 layout are clean CLI errors, not tracebacks."""
    import h5py

    from psana_ray_tpu.models.peaks import (
        CxiWriter, PeakSet, merge_cxi, merge_cxi_main, read_cxi_peaksets,
    )

    mk = lambda i, v: PeakSet(  # noqa: E731
        event_idx=i, shard_rank=i % 2,
        y=np.array([v], np.float32), x=np.array([v], np.float32),
        intensity=np.array([0.5], np.float32), photon_energy=9.0,
    )
    src = str(tmp_path / "src.cxi")
    with CxiWriter(src, max_peaks=8) as w:
        w.append([mk(i, float(i)) for i in range(7)])
    out = str(tmp_path / "chunked.cxi")
    assert merge_cxi([src], out, chunk_events=2) == 7
    sets = read_cxi_peaksets(out)
    # sorted by (shard_rank, event_idx): evens (rank 0) then odds (rank 1)
    assert [p.event_idx for p in sets] == [0, 2, 4, 6, 1, 3, 5]
    assert all(p.y[0] == p.event_idx for p in sets)

    rc = merge_cxi_main([str(tmp_path / "nope.cxi"), "--output",
                         str(tmp_path / "x.cxi")])
    assert rc == 1  # missing input: clean error, not an h5py traceback

    foreign = str(tmp_path / "foreign.h5")
    with h5py.File(foreign, "w") as f:
        f.create_dataset("d", data=[1])
    rc = merge_cxi_main([foreign, "--output", str(tmp_path / "y.cxi")])
    assert rc == 1  # foreign layout: refused with the ValueError message


def test_merge_cxi_interleaved_files_chunked(tmp_path):
    """Winners interleave across input files within one output slab (the
    batched pass-2 read groups rows by file and must reassemble them in
    sorted key order): odd events in one file, even in the other, with a
    chunk smaller than either file's contribution."""
    from psana_ray_tpu.cxi import CxiWriter, PeakSet, merge_cxi, read_cxi_peaksets

    mk = lambda i: PeakSet(  # noqa: E731
        event_idx=i, shard_rank=0,
        y=np.array([float(i)], np.float32), x=np.array([0.0], np.float32),
        intensity=np.array([1.0], np.float32), photon_energy=8.0,
    )
    evens, odds = str(tmp_path / "e.cxi"), str(tmp_path / "o.cxi")
    with CxiWriter(evens, max_peaks=4) as w:
        w.append([mk(i) for i in range(0, 20, 2)])
    with CxiWriter(odds, max_peaks=4) as w:
        w.append([mk(i) for i in range(1, 20, 2)])
    out = str(tmp_path / "m.cxi")
    assert merge_cxi([evens, odds], out, chunk_events=3) == 20
    sets = read_cxi_peaksets(out)
    assert [p.event_idx for p in sets] == list(range(20))
    assert all(p.y[0] == p.event_idx for p in sets)  # rows from right file

    with pytest.raises(ValueError, match="chunk_events"):
        merge_cxi([evens], str(tmp_path / "z.cxi"), chunk_events=0)


def test_merge_cxi_cli(tmp_path):
    from psana_ray_tpu.models.peaks import CxiWriter, PeakSet, merge_cxi_main, read_cxi_peaks

    p = str(tmp_path / "a.cxi")
    with CxiWriter(p, max_peaks=4) as w:
        w.append([PeakSet(event_idx=7, shard_rank=1,
                          y=np.array([1.0], np.float32),
                          x=np.array([2.0], np.float32),
                          intensity=np.array([0.9], np.float32))])
    out = str(tmp_path / "m.cxi")
    assert merge_cxi_main([p, p, "--output", out]) == 0
    n, *_, ev = read_cxi_peaks(out)
    assert len(n) == 1 and int(ev[0]) == 7  # self-merge dedupes


def test_mode_mismatch_refused(serving_ckpt, tmp_path):
    """--mode throughput against an s2d=2 checkpoint must refuse (the
    operating mode is a property of the trained tree)."""
    from psana_ray_tpu.sfx import main

    rc = main([
        "--serving_params", serving_ckpt,
        "--mode", "throughput",
        "--output", str(tmp_path / "x.cxi"),
    ])
    assert rc == 1


def test_cxi_append_refuses_foreign_hdf5(tmp_path):
    """mode='a' on a valid HDF5 file that is not a CxiWriter file must
    raise a clear ValueError (and release the handle), not a KeyError."""
    import h5py

    from psana_ray_tpu.models.peaks import CxiWriter

    path = str(tmp_path / "foreign.h5")
    with h5py.File(path, "w") as f:
        f.create_dataset("something_else", data=[1, 2, 3])
    with pytest.raises(ValueError, match="foreign"):
        CxiWriter(path, mode="a")
    # handle released: the file can be reopened for writing immediately
    with h5py.File(path, "r+") as f:
        assert "something_else" in f


def test_raw_stream_with_on_device_calibration(serving_ckpt, tmp_path):
    """The --calib_npz serving shape: the stream carries RAW ADUs and the
    compiled step runs fused calibration in FRONT of the net. Pins the
    gain convention — the npz gain is ABSOLUTE (ADUs/photon, i.e.
    spec.adu_gain * relative map): with it, peaks recover the planted
    truth like the calib-stream path; the relative map alone would feed
    the net 35x-hot frames (the examples/train_peaknet.py trap)."""
    from psana_ray_tpu.checkpoint import load_params
    from psana_ray_tpu.config import PipelineConfig, SourceConfig
    from psana_ray_tpu.models.peaks import CxiWriter
    from psana_ray_tpu.producer import ProducerRuntime
    from psana_ray_tpu.sfx import SfxConfig, SfxPipeline
    from psana_ray_tpu.sources import SyntheticSource
    from psana_ray_tpu.sources.base import DETECTORS
    from psana_ray_tpu.transport.addressing import open_queue

    # calibration constants from the SAME run as the stream: pedestal /
    # gain / mask are seeded per (exp, run, seed), so a default run=1
    # source would calibrate run-2 frames with mismatched constants
    src = SyntheticSource(run=EVAL_RUN, num_events=1, detector_name=DET, seed=SEED)
    calib = (
        src.pedestal(),
        src.spec.adu_gain * src.gain_map(),  # ABSOLUTE gain -> photons out
        src.create_bad_pixel_mask(),
    )

    cfg = PipelineConfig(
        source=SourceConfig(
            exp="synthetic", run=EVAL_RUN, num_events=N_EVENTS,
            detector_name=DET, seed=SEED, mode="raw",
        )
    )
    ProducerRuntime(cfg).run(block=False)
    queue = open_queue(cfg.transport)

    cxi = str(tmp_path / "raw.cxi")
    variables = load_params(serving_ckpt)
    with CxiWriter(cxi, max_peaks=64) as writer:
        pipe = SfxPipeline(
            variables, writer, calib=calib, config=SfxConfig(batch_size=4),
        )
        n = pipe.run(queue)
    assert n == N_EVENTS

    h = DETECTORS[DET].height
    m, events = _score_cxi(cxi, h)
    assert events == set(range(N_EVENTS))
    # same physics bar as the calib-stream e2e: the on-device chain must
    # hand the net the same photon-scale distribution it trained on
    assert m["recall"] >= 0.6, m
    assert m["precision"] >= 0.8, m
