"""Per-record stage timing: hop stamps through the record envelope
decompose e2e latency into named stages (enqueue, queue_dwell, dequeue,
batch, device_put, dispatch).

Acceptance (ISSUE 1): on a synthetic-source e2e run the per-stage sum is
within 20% of the measured e2e latency. The decomposition is telescoping
(consecutive differences of one record's timeline), so the per-record sum
is EXACT; the 20% tolerance covers the reservoir/mean estimators only."""

import threading
import time

import numpy as np
import pytest

from psana_ray_tpu.infeed import InfeedPipeline
from psana_ray_tpu.obs.stages import (
    HOP_ENQ,
    HOP_SRC,
    STAGE_E2E,
    STAGES,
    observe_record_stages,
)
from psana_ray_tpu.records import EndOfStream, FrameRecord, mark_hop
from psana_ray_tpu.transport.ring import RingBuffer
from psana_ray_tpu.utils.metrics import StageTimes


def _make_record(i, shape=(1, 8, 8)):
    return FrameRecord(0, i, np.full(shape, float(i), np.float32), 9.0)


class TestHopStamps:
    def test_mark_hop_lazy_allocation(self):
        rec = _make_record(0)
        assert rec.hops is None  # zero cost until someone times the stream
        mark_hop(rec, HOP_SRC)
        assert HOP_SRC in rec.hops
        mark_hop(rec, HOP_ENQ, t=123.0)
        assert rec.hops[HOP_ENQ] == 123.0

    def test_mark_hop_ignores_non_frames(self):
        eos = EndOfStream(total_events=4)
        mark_hop(eos, HOP_SRC)  # no-op, no crash

    def test_hops_never_cross_the_wire(self):
        rec = _make_record(1)
        mark_hop(rec, HOP_SRC)
        back = FrameRecord.from_bytes(rec.to_bytes())
        assert back.hops is None  # monotonic stamps are process-local

    def test_telescoping_with_missing_boundary(self):
        st = StageTimes()
        # 'deq' missing: the stage ending at the next boundary ('push' ->
        # dequeue) absorbs the gap; stages still sum to last-first
        hops = {"src": 0.0, "enq": 1.0, "push": 4.0, "batch": 5.0, "device_put": 6.0}
        observe_record_stages(st, hops, t_end=8.0)
        snap = st.snapshot()
        total = sum(
            snap[s]["mean_ms"] for s in STAGES if s in snap
        )
        assert total == pytest.approx(8.0 * 1e3)
        assert snap[STAGE_E2E]["mean_ms"] == pytest.approx(8.0 * 1e3)


class TestE2EDecomposition:
    @pytest.mark.parametrize("batch_size", [4])
    def test_stage_sum_matches_e2e(self, batch_size):
        """Synthetic source -> ring -> batcher -> device_put -> step, with
        every record stamped; per-stage means must sum to the e2e mean
        (exactly, modulo estimator noise — assert the 20% criterion)."""
        n = 32
        queue = RingBuffer(maxsize=8)

        def produce():
            for i in range(n):
                rec = _make_record(i)
                mark_hop(rec, HOP_SRC)
                while not queue.put(rec):
                    time.sleep(0.0005)
                mark_hop(rec, HOP_ENQ)
                if i % 8 == 3:
                    time.sleep(0.002)  # visible queue-dwell variation
            assert queue.put_wait(EndOfStream(total_events=n), timeout=30.0)

        t_prod = threading.Thread(target=produce, daemon=True)
        pipe = InfeedPipeline(
            queue, batch_size=batch_size, prefetch_depth=2, poll_interval_s=0.001
        )
        t_prod.start()
        seen = pipe.run(lambda b: b.frames.sum(), block_until_ready=True)
        t_prod.join()
        assert seen == n

        snap = pipe.metrics.stages.snapshot()
        # every named stage observed, once per record
        for stage in STAGES:
            assert stage in snap, f"stage {stage!r} missing from {sorted(snap)}"
            assert snap[stage]["count"] == n
        assert snap[STAGE_E2E]["count"] == n

        stage_sum = sum(snap[s]["mean_ms"] for s in STAGES)
        e2e = snap[STAGE_E2E]["mean_ms"]
        assert e2e > 0
        # acceptance: decomposition within 20% of measured e2e
        assert stage_sum == pytest.approx(e2e, rel=0.20)
        # queue-dwell must have picked up the injected producer sleeps
        assert snap["queue_dwell"]["mean_ms"] > 0

    def test_untimed_stream_records_no_stages(self):
        """Zero-cost-when-disabled: without mark_hop the same pipeline
        run observes nothing (batch.hops stays None end to end)."""
        n = 8
        queue = RingBuffer(maxsize=8)

        def produce():
            for i in range(n):
                while not queue.put(_make_record(i)):
                    time.sleep(0.0005)
            assert queue.put_wait(EndOfStream(total_events=n), timeout=30.0)

        t_prod = threading.Thread(target=produce, daemon=True)
        pipe = InfeedPipeline(queue, batch_size=4, poll_interval_s=0.001)
        t_prod.start()
        seen = pipe.run(lambda b: b.frames.sum(), block_until_ready=True)
        t_prod.join()
        assert seen == n
        assert pipe.metrics.stages.snapshot() == {}

    def test_stages_flow_to_prometheus(self):
        """The same histograms surface as psana_ray_stages_* gauges."""
        import re

        from psana_ray_tpu.obs import MetricsRegistry

        n = 8
        queue = RingBuffer(maxsize=8)

        def produce():
            for i in range(n):
                rec = _make_record(i)
                mark_hop(rec, HOP_SRC)
                while not queue.put(rec):
                    time.sleep(0.0005)
                mark_hop(rec, HOP_ENQ)
            assert queue.put_wait(EndOfStream(total_events=n), timeout=30.0)

        t_prod = threading.Thread(target=produce, daemon=True)
        pipe = InfeedPipeline(queue, batch_size=4, poll_interval_s=0.001)
        t_prod.start()
        pipe.run(lambda b: b.frames.sum(), block_until_ready=True)
        t_prod.join()

        reg = MetricsRegistry()
        reg.register("consumer", pipe.metrics)
        text = reg.render_prometheus()
        for stage in STAGES:
            pat = rf'^psana_ray_stages_{stage}_p50_ms\{{source="consumer"\}} \S+$'
            assert re.search(pat, text, re.M), f"missing {stage} gauge in:\n{text}"

    def test_named_pipeline_registers_and_unregisters(self):
        from psana_ray_tpu.obs import MetricsRegistry

        queue = RingBuffer(maxsize=8)
        queue.put(EndOfStream(total_events=0))
        pipe = InfeedPipeline(queue, batch_size=4, poll_interval_s=0.001, name="epix")
        assert "infeed.epix" in MetricsRegistry.default().sources()
        pipe.run(lambda b: b.frames.sum())
        assert "infeed.epix" not in MetricsRegistry.default().sources()
