"""Ring + Ulysses attention vs the single-device oracle on an 8-wide seq
mesh axis."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from psana_ray_tpu.parallel import create_mesh
from psana_ray_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
    ulysses_attention,
)


@pytest.fixture(scope="module")
def seq_mesh():
    return create_mesh(("data", "seq"), (1, 8))


def _qkv(b=2, s=64, h=8, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32)) * 0.3
    return mk(), mk(), mk()


def _shard(x, mesh):
    return jax.device_put(x, NamedSharding(mesh, P(None, "seq", None, None)))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(seq_mesh, causal):
    q, k, v = _qkv()
    want = np.asarray(reference_attention(q, k, v, causal=causal))
    got = np.asarray(
        ring_attention(
            _shard(q, seq_mesh), _shard(k, seq_mesh), _shard(v, seq_mesh),
            seq_mesh, causal=causal,
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(seq_mesh, causal):
    q, k, v = _qkv(seed=1)
    want = np.asarray(reference_attention(q, k, v, causal=causal))
    got = np.asarray(
        ulysses_attention(
            _shard(q, seq_mesh), _shard(k, seq_mesh), _shard(v, seq_mesh),
            seq_mesh, causal=causal,
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_ulysses_rejects_bad_heads(seq_mesh):
    q, k, v = _qkv(h=6)  # 6 % 8 != 0
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(_shard(q, seq_mesh), _shard(k, seq_mesh), _shard(v, seq_mesh), seq_mesh)


def test_ring_under_jit_and_grad(seq_mesh):
    # ring attention must be differentiable and jittable (training path)
    q, k, v = _qkv(b=1, s=32, h=4, d=8)

    @jax.jit
    def loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, seq_mesh) ** 2)

    g = jax.grad(loss)(_shard(q, seq_mesh), _shard(k, seq_mesh), _shard(v, seq_mesh))
    assert np.isfinite(np.asarray(g)).all()

    @jax.jit
    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    g_ref = jax.grad(loss_ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-3, atol=1e-4)


class TestRingFlashAttention:
    """ring_flash_attention (per-hop flash + LSE combining) must match the
    single-device oracle exactly — on the CPU test backend the hops run
    the XLA statistics fallback, which shares the combining math with the
    TPU Pallas path."""

    @pytest.fixture
    def seq_mesh(self):
        return create_mesh(("seq",), (8,))

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_oracle(self, rng, seq_mesh, causal):
        from psana_ray_tpu.parallel import ring_flash_attention
        from psana_ray_tpu.parallel.ring_attention import reference_attention

        b, s, h, d = 2, 32, 4, 8
        q = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
        ref = reference_attention(q, k, v, causal=causal)
        got = ring_flash_attention(q, k, v, seq_mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_jit_sharded(self, rng, seq_mesh):
        from jax.sharding import NamedSharding

        from psana_ray_tpu.parallel import ring_flash_attention
        from psana_ray_tpu.parallel.ring_attention import reference_attention

        b, s, h, d = 1, 16, 2, 8
        mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
        q, k, v = mk(), mk(), mk()
        sh = NamedSharding(seq_mesh, P(None, "seq", None, None))
        q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
        f = jax.jit(
            lambda q, k, v: ring_flash_attention(q, k, v, seq_mesh, causal=True)
        )
        got = f(q, k, v)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_single_device_flash_wrapper(self, rng):
        from psana_ray_tpu.parallel import flash_attention
        from psana_ray_tpu.parallel.ring_attention import reference_attention

        b, s, h, d = 2, 24, 3, 8
        q = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(flash_attention(q, k, v, causal=True)),
            np.asarray(reference_attention(q, k, v, causal=True)),
            rtol=2e-5, atol=2e-5,
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_grad_parity_with_ring_attention(self, rng, seq_mesh, causal):
        """VERDICT r3 #9: ring_flash_attention must be trainable — its
        gradients (through the per-hop stats VJP, the LSE hop-combine,
        the causal lax.switch, and the ppermute rotation) must match the
        differentiable XLA ring on the 8-device mesh."""
        from psana_ray_tpu.parallel import ring_flash_attention
        from psana_ray_tpu.parallel.ring_attention import ring_attention

        b, s, h, d = 1, 32, 2, 8
        mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32)) * 0.4
        q, k, v = mk(), mk(), mk()
        q, k, v = (_shard(x, seq_mesh) for x in (q, k, v))
        w = _shard(jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32)), seq_mesh)

        def loss(attn):
            def f(q, k, v):
                return jnp.sum(attn(q, k, v, seq_mesh, causal=causal) * w)

            return f

        got = jax.grad(loss(ring_flash_attention), argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss(ring_attention), argnums=(0, 1, 2))(q, k, v)
        for name, g, r in zip("qkv", got, want):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(r), rtol=2e-4, atol=2e-5,
                err_msg=f"d{name} mismatch",
            )

    def test_grad_under_jit_sharded(self, rng, seq_mesh):
        from psana_ray_tpu.parallel import ring_flash_attention

        b, s, h, d = 1, 16, 2, 8
        mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
        q, k, v = (_shard(mk(), seq_mesh) for _ in range(3))

        g = jax.jit(
            jax.grad(
                lambda q, k, v: jnp.sum(
                    ring_flash_attention(q, k, v, seq_mesh, causal=True) ** 2
                ),
                argnums=(0, 1, 2),
            )
        )(q, k, v)
        for x in g:
            arr = np.asarray(x)
            assert np.isfinite(arr).all()
            assert np.abs(arr).max() > 0

    def test_bf16_ring_matches_oracle(self, rng, seq_mesh):
        """bf16 q/k/v through the ring: the f32 stats carry must keep the
        lax.switch branches dtype-stable (round-2 ADVICE: the kernel path
        emitted f32 lse while the causal skip branch returned bf16)."""
        from psana_ray_tpu.parallel import ring_flash_attention
        from psana_ray_tpu.parallel.ring_attention import reference_attention

        b, s, h, d = 2, 32, 4, 8
        mk = lambda: jnp.asarray(
            rng.normal(size=(b, s, h, d)).astype(np.float32)
        ).astype(jnp.bfloat16)
        q, k, v = mk(), mk(), mk()
        got = ring_flash_attention(q, k, v, seq_mesh, causal=True)
        assert got.dtype == jnp.bfloat16
        ref = reference_attention(
            q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
            causal=True,
        )
        np.testing.assert_allclose(
            np.asarray(got, dtype=np.float32), np.asarray(ref), rtol=0.0, atol=3e-2
        )


class TestVendoredFlashKernel:
    """Interpret-mode equivalence of the vendored Pallas flash kernel
    (parallel/flash.py — replaces round 2's private
    ``fa._flash_attention_impl`` dependency) against the XLA statistics
    formulation, on the dtypes the serving path actually uses."""

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_kernel_matches_xla_stats(self, rng, causal, dtype):
        from psana_ray_tpu.parallel.flash import (
            _pallas_attention_with_stats,
            _xla_attention_with_stats,
        )

        b, h, s, d = 2, 3, 256, 128
        mk = lambda: jnp.asarray(
            rng.normal(size=(b, h, s, d)).astype(np.float32) * 0.3
        ).astype(dtype)
        q, k, v = mk(), mk(), mk()
        o_ref, lse_ref = _xla_attention_with_stats(q, k, v, causal)
        o_pl, lse_pl = _pallas_attention_with_stats(q, k, v, causal, interpret=True)
        assert o_pl.dtype == dtype
        assert lse_pl.dtype == jnp.float32 and lse_ref.dtype == jnp.float32
        tol = 2e-2 if dtype == jnp.bfloat16 else 2e-5
        np.testing.assert_allclose(
            np.asarray(o_pl, dtype=np.float32),
            np.asarray(o_ref, dtype=np.float32),
            rtol=0.0, atol=tol,
        )
        np.testing.assert_allclose(
            np.asarray(lse_pl), np.asarray(lse_ref), rtol=0.0, atol=1e-2
        )

    def test_uneven_kv_length(self, rng):
        from psana_ray_tpu.parallel.flash import (
            _pallas_attention_with_stats,
            _xla_attention_with_stats,
        )

        q = jnp.asarray(rng.normal(size=(1, 2, 128, 128)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 2, 384, 128)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 2, 384, 128)).astype(np.float32))
        o_ref, lse_ref = _xla_attention_with_stats(q, k, v, False)
        o_pl, lse_pl = _pallas_attention_with_stats(q, k, v, False, interpret=True)
        np.testing.assert_allclose(np.asarray(o_pl), np.asarray(o_ref), atol=3e-5)
        np.testing.assert_allclose(np.asarray(lse_pl), np.asarray(lse_ref), atol=1e-3)

    @pytest.mark.parametrize("causal", [False, True])
    def test_stats_vjp_handles_lse_cotangent(self, rng, causal):
        """attention_with_stats' VJP must differentiate BOTH outputs —
        the lse cotangent folds into the backward's delta term. Oracle:
        plain autodiff of the XLA stats formulation (no custom_vjp)."""
        from psana_ray_tpu.parallel.flash import (
            _xla_attention_with_stats,
            attention_with_stats,
        )

        b, h, s, d = 1, 2, 8, 8
        mk = lambda: jnp.asarray(rng.normal(size=(b, h, s, d)).astype(np.float32)) * 0.4
        q, k, v = mk(), mk(), mk()
        wo = jnp.asarray(rng.normal(size=(b, h, s, d)).astype(np.float32))
        wl = jnp.asarray(rng.normal(size=(b, h, s)).astype(np.float32))

        def loss(fn):
            def f(q, k, v):
                o, lse = fn(q, k, v, causal)
                # both outputs in the loss: a wrong/ignored lse cotangent
                # cannot hide
                return jnp.sum(o * wo) + jnp.sum(lse * wl)

            return f

        got = jax.grad(loss(attention_with_stats), argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss(_xla_attention_with_stats), argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=2e-4, atol=2e-5)


class TestFlashBackward:
    """The flash VJP (tile-regenerated probabilities from saved lse):
    Pallas backward kernels in interpret mode vs the XLA backward from the
    same residuals, and the custom_vjp end-to-end vs autodiff of the
    reference formulation."""

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_pallas_bwd_matches_xla_bwd(self, rng, causal, dtype):
        from psana_ray_tpu.parallel.flash import (
            _pallas_attention_bwd,
            _xla_attention_bwd,
            _xla_attention_with_stats,
        )

        b, h, s, d = 2, 2, 256, 128
        mk = lambda: jnp.asarray(
            rng.normal(size=(b, h, s, d)).astype(np.float32) * 0.3
        ).astype(dtype)
        q, k, v = mk(), mk(), mk()
        o, lse = _xla_attention_with_stats(q, k, v, causal)
        do = mk()
        want = _xla_attention_bwd(q, k, v, o, lse, do, causal)
        got = _pallas_attention_bwd(q, k, v, o, lse, do, causal, interpret=True)
        tol = 5e-2 if dtype == jnp.bfloat16 else 1e-4
        for g, w, name in zip(got, want, ("dq", "dk", "dv")):
            assert g.dtype == dtype, name
            np.testing.assert_allclose(
                np.asarray(g, np.float32), np.asarray(w, np.float32),
                rtol=0.0, atol=tol, err_msg=name,
            )

    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_bwd_dlse_matches_xla_bwd(self, rng, causal):
        """The lse-cotangent path (delta → delta − dlse) through the
        SAME backward kernels, interpret mode vs the XLA backward."""
        from psana_ray_tpu.parallel.flash import (
            _pallas_attention_bwd,
            _xla_attention_bwd,
            _xla_attention_with_stats,
        )

        b, h, s, d = 1, 2, 256, 128
        mk = lambda: jnp.asarray(rng.normal(size=(b, h, s, d)).astype(np.float32) * 0.3)
        q, k, v = mk(), mk(), mk()
        o, lse = _xla_attention_with_stats(q, k, v, causal)
        do = mk()
        dlse = jnp.asarray(rng.normal(size=(b, h, s)).astype(np.float32))
        want = _xla_attention_bwd(q, k, v, o, lse, do, causal, dlse=dlse)
        got = _pallas_attention_bwd(
            q, k, v, o, lse, do, causal, interpret=True, dlse=dlse
        )
        for g, w, name in zip(got, want, ("dq", "dk", "dv")):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=0.0, atol=1e-4, err_msg=name
            )

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_attention_grad_matches_reference_autodiff(self, rng, causal):
        from psana_ray_tpu.parallel.flash import flash_attention

        b, s, h, d = 2, 64, 4, 16  # [B, S, H, D] repo layout; XLA paths on CPU
        mk = lambda: jnp.asarray(
            rng.normal(size=(b, s, h, d)).astype(np.float32) * 0.5
        )
        q, k, v = mk(), mk(), mk()

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

        got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for g, w, name in zip(got, want, ("dq", "dk", "dv")):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=1e-4, atol=1e-5, err_msg=name
            )

    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_bwd_uneven_kv(self, rng, causal):
        # causal here exercises _tile_live tile-skipping where sk > sq:
        # key blocks entirely beyond every query row must contribute
        # exactly-zero dk/dv through the reset/finalize structure
        from psana_ray_tpu.parallel.flash import (
            _pallas_attention_bwd,
            _xla_attention_bwd,
            _xla_attention_with_stats,
        )

        q = jnp.asarray(rng.normal(size=(1, 2, 128, 128)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 2, 384, 128)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 2, 384, 128)).astype(np.float32))
        o, lse = _xla_attention_with_stats(q, k, v, causal)
        do = jnp.asarray(rng.normal(size=(1, 2, 128, 128)).astype(np.float32))
        want = _xla_attention_bwd(q, k, v, o, lse, do, causal)
        got = _pallas_attention_bwd(q, k, v, o, lse, do, causal, interpret=True)
        for g, w, name in zip(got, want, ("dq", "dk", "dv")):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=0.0, atol=1e-4, err_msg=name
            )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_matches_reference_and_grads(seq_mesh, causal):
    q, k, v = _qkv(seed=3)
    want = np.asarray(reference_attention(q, k, v, causal=causal))
    qs, ks, vs = (_shard(x, seq_mesh) for x in (q, k, v))
    got = np.asarray(
        ulysses_attention(qs, ks, vs, seq_mesh, causal=causal, impl="flash")
    )
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    # trainability: grads through the sharded flash path == reference grads
    def loss_flash(q, k, v):
        return jnp.sum(
            ulysses_attention(q, k, v, seq_mesh, causal=causal, impl="flash") ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    got_g = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(qs, ks, vs)
    want_g = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got_g, want_g, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=1e-4, atol=1e-5, err_msg=name
        )


class TestPickBlocks:
    """Block-shape selection invariants (the 6x kernel lever — see
    PERF_NOTES.md round-4 section): picked blocks must divide the
    sequence lengths and respect both VMEM footprint caps."""

    def test_vit_serving_shape(self):
        from psana_ray_tpu.parallel.flash import _pick_blocks

        bq, bk = _pick_blocks(8448, 8448, 128)
        assert (bq, bk) == (384, 1408)  # measured near-plateau point

    @pytest.mark.parametrize("sq,sk,d", [
        (128, 128, 128), (512, 512, 128), (384, 1152, 128),
        (8448, 8448, 128), (256, 8192, 512), (128, 8192, 1024),
    ])
    def test_invariants(self, sq, sk, d):
        """Every shape the kernels ACCEPT (d <= _MAX_HEAD_DIM) satisfies
        the VMEM caps STRICTLY, forward and backward — the >=128 block
        floor can no longer void them because _kernel_shapes_ok routes
        larger head dims to the XLA fallback (ADVICE r4)."""
        from psana_ray_tpu.parallel.flash import (
            _MAX_KV_TILE_ELEMS, _MAX_TILE_ELEMS, _pick_blocks,
        )

        for backward, div in ((False, 1), (True, 2)):
            bq, bk = _pick_blocks(sq, sk, d, backward=backward)
            assert sq % bq == 0 and sk % bk == 0
            assert bq % 128 == 0 and bk % 128 == 0
            assert bq * bk <= _MAX_TILE_ELEMS // div
            assert bk * d <= _MAX_KV_TILE_ELEMS // div

    def test_large_head_dim_rejected(self):
        """d beyond _MAX_HEAD_DIM (where even a 128-wide block would blow
        the backward kv-tile cap) must not reach the kernel."""
        import jax.numpy as jnp

        from psana_ray_tpu.parallel.flash import (
            _MAX_HEAD_DIM, _kernel_shapes_ok,
        )

        ok = jnp.zeros((1, 1, 128, _MAX_HEAD_DIM), jnp.bfloat16)
        big = jnp.zeros((1, 1, 128, 2 * _MAX_HEAD_DIM), jnp.bfloat16)
        assert _kernel_shapes_ok(ok, ok)
        assert not _kernel_shapes_ok(big, big)
