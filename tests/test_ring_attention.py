"""Ring + Ulysses attention vs the single-device oracle on an 8-wide seq
mesh axis."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from psana_ray_tpu.parallel import create_mesh
from psana_ray_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
    ulysses_attention,
)


@pytest.fixture(scope="module")
def seq_mesh():
    return create_mesh(("data", "seq"), (1, 8))


def _qkv(b=2, s=64, h=8, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32)) * 0.3
    return mk(), mk(), mk()


def _shard(x, mesh):
    return jax.device_put(x, NamedSharding(mesh, P(None, "seq", None, None)))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(seq_mesh, causal):
    q, k, v = _qkv()
    want = np.asarray(reference_attention(q, k, v, causal=causal))
    got = np.asarray(
        ring_attention(
            _shard(q, seq_mesh), _shard(k, seq_mesh), _shard(v, seq_mesh),
            seq_mesh, causal=causal,
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(seq_mesh, causal):
    q, k, v = _qkv(seed=1)
    want = np.asarray(reference_attention(q, k, v, causal=causal))
    got = np.asarray(
        ulysses_attention(
            _shard(q, seq_mesh), _shard(k, seq_mesh), _shard(v, seq_mesh),
            seq_mesh, causal=causal,
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_ulysses_rejects_bad_heads(seq_mesh):
    q, k, v = _qkv(h=6)  # 6 % 8 != 0
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(_shard(q, seq_mesh), _shard(k, seq_mesh), _shard(v, seq_mesh), seq_mesh)


def test_ring_under_jit_and_grad(seq_mesh):
    # ring attention must be differentiable and jittable (training path)
    q, k, v = _qkv(b=1, s=32, h=4, d=8)

    @jax.jit
    def loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, seq_mesh) ** 2)

    g = jax.grad(loss)(_shard(q, seq_mesh), _shard(k, seq_mesh), _shard(v, seq_mesh))
    assert np.isfinite(np.asarray(g)).all()

    @jax.jit
    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    g_ref = jax.grad(loss_ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-3, atol=1e-4)
