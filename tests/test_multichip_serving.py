"""Multi-device serving correctness for the flagship path (config 4).

Round-2 VERDICT weak #4: the fused-Pallas calib + ResNet-50 serving path
never ran on a multi-device mesh anywhere. Here the full fused path runs
under shard_map with the batch sharded P('data') on the 8-device virtual
CPU mesh (kernels in interpret mode) and must produce exactly the
single-device result — the grid is over the batch, so sharding the batch
must be a pure partition of the same per-sample math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from psana_ray_tpu.parallel.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from psana_ray_tpu.models import panels_to_nhwc
from psana_ray_tpu.models.pallas_resnet import resnet_fused_infer
from psana_ray_tpu.models.resnet import ResNetClassifier
from psana_ray_tpu.ops import fused_calibrate
from psana_ray_tpu.parallel import create_mesh

STAGE_SIZES = (1, 1)  # interpret-mode-sized ResNet, same kernel code paths


@pytest.fixture(scope="module")
def setup(request):
    rng = np.random.default_rng(0)
    panels, h, w = 2, 32, 32
    pedestal = jnp.asarray(rng.normal(90.0, 3.0, (panels, h, w)).astype(np.float32))
    gain = jnp.asarray((1.0 + 0.05 * rng.standard_normal((panels, h, w))).astype(np.float32))
    mask = jnp.asarray((rng.random((panels, h, w)) > 0.02).astype(np.float32))
    frames = jnp.asarray(
        (rng.normal(100.0, 12.0, (8, panels, h, w))).astype(np.float32)
    )
    model = ResNetClassifier(stage_sizes=STAGE_SIZES, num_classes=2, width=8, norm="frozen")
    variables = model.init(jax.random.key(0), jnp.zeros((1, h, w, panels)))
    return pedestal, gain, mask, frames, variables


def _serve(variables, frames, pedestal, gain, mask):
    c = fused_calibrate(
        frames, pedestal, gain, mask, threshold=10.0, out_dtype=jnp.bfloat16
    )
    return resnet_fused_infer(
        variables, panels_to_nhwc(c), stage_sizes=STAGE_SIZES, interpret=True
    )


def test_sharded_batch_equals_single_device(setup):
    pedestal, gain, mask, frames, variables = setup
    mesh = create_mesh(("data",), (8,))

    single = _serve(variables, frames, pedestal, gain, mask)

    sharded = shard_map(
        lambda v, f: _serve(v, f, pedestal, gain, mask),
        mesh=mesh,
        in_specs=(P(), P("data")),
        out_specs=P("data"),
        check_vma=False,
    )
    x = jax.device_put(frames, NamedSharding(mesh, P("data")))
    got = sharded(variables, x)

    assert got.sharding.spec == P("data")
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(single, np.float32), rtol=0, atol=1e-5
    )


def test_sharded_serving_under_jit(setup):
    """The production form: jit(shard_map(...)) — one compiled program per
    process feeding its local devices."""
    pedestal, gain, mask, frames, variables = setup
    mesh = create_mesh(("data",), (8,))

    serve = jax.jit(
        shard_map(
            lambda v, f: _serve(v, f, pedestal, gain, mask),
            mesh=mesh,
            in_specs=(P(), P("data")),
            out_specs=P("data"),
            check_vma=False,
        )
    )
    x = jax.device_put(frames, NamedSharding(mesh, P("data")))
    got = serve(variables, x)
    single = _serve(variables, frames, pedestal, gain, mask)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(single, np.float32), rtol=0, atol=1e-5
    )
