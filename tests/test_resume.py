"""CLI-reachable resume: kill a producer+consumer mid-stream, restart both
from the consumer-written StreamCursor, and verify every event is processed
at-least-once with no gap.

The reference loses all position on restart (its ``iter_events`` has no
cursor, reference ``producer.py:88``; SURVEY.md §5 "a restarted producer
restarts the run from the beginning"). Here the consumer CLI persists a
contiguous per-shard watermark (``--cursor_path``) and the producer CLI
resumes past it (``--cursor_path`` / ``--start_event``).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_EVENTS = 200


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _start_server(port, env):
    return subprocess.Popen(
        [sys.executable, "-m", "psana_ray_tpu.queue_server",
         "--host", "127.0.0.1", "--port", str(port), "--queue_size", "64"],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )


def _producer_cmd(port, cursor):
    return [
        sys.executable, "-m", "psana_ray_tpu.producer",
        "--exp", "synthetic", "--num_events", str(N_EVENTS),
        "--detector_name", "smoke_a",
        "--address", f"tcp://127.0.0.1:{port}",
        "--queue_name", "rq", "--num_consumers", "1",
        "--cursor_path", cursor,
    ]


def _consumer_cmd(port, cursor):
    return [
        sys.executable, "-m", "psana_ray_tpu.consumer", "0",
        "--address", f"tcp://127.0.0.1:{port}",
        "--queue_name", "rq",
        "--cursor_path", cursor, "--cursor_save_every", "1",
    ]


def _processed_indices(text):
    out = set()
    for line in text.splitlines():
        if "idx=" in line and "rank=" in line:
            out.add(int(line.split("idx=")[1].split()[0]))
    return out


def test_kill_and_resume_covers_every_event(tmp_path):
    env = _env()
    cursor = str(tmp_path / "stream.cursor.json")
    out1_path = tmp_path / "consumer1.out"

    # --- run 1: full stream launched, both sides SIGKILLed mid-flight ----
    port1 = _free_port()
    server1 = _start_server(port1, env)
    producer1 = consumer1 = None
    try:
        producer1 = subprocess.Popen(
            _producer_cmd(port1, cursor), env=env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        with open(out1_path, "w") as f1:
            consumer1 = subprocess.Popen(
                _consumer_cmd(port1, cursor), env=env, cwd=REPO,
                stdout=f1, stderr=subprocess.STDOUT, text=True,
            )
            # wait for real mid-stream progress (watermark >= 20), then
            # SIGKILL producer and consumer — no graceful teardown
            deadline = time.monotonic() + 120
            watermark = -1
            while time.monotonic() < deadline:
                if os.path.exists(cursor):
                    with open(cursor) as f:
                        pos = json.load(f).get("positions", {})
                    watermark = int(pos.get("0", -1))
                    if watermark >= 20:
                        break
                time.sleep(0.02)
            assert watermark >= 20, f"no mid-stream progress (watermark={watermark})"
            # the stream must still be live — killing after completion
            # would test nothing
            assert producer1.poll() is None or watermark < N_EVENTS - 1
            producer1.kill()
            consumer1.kill()
            producer1.wait(timeout=30)
            consumer1.wait(timeout=30)
    finally:
        for proc in (producer1, consumer1):
            if proc is not None and proc.poll() is None:
                proc.kill()
        server1.kill()
        server1.wait(timeout=15)

    done1 = _processed_indices(out1_path.read_text())
    assert done1, "consumer 1 processed nothing"
    with open(cursor) as f:
        saved = json.load(f)
    resume_at = int(saved["positions"]["0"]) + 1
    assert 20 <= resume_at <= len(done1) + 1  # contiguous watermark semantics

    # --- run 2: fresh server, both sides restarted from the cursor -------
    port2 = _free_port()
    server2 = _start_server(port2, env)
    try:
        producer2 = subprocess.Popen(
            _producer_cmd(port2, cursor), env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        consumer2 = subprocess.run(
            _consumer_cmd(port2, cursor), env=env, cwd=REPO,
            capture_output=True, text=True, timeout=300,
        )
        p_out, _ = producer2.communicate(timeout=120)
        assert producer2.returncode == 0, p_out[-2000:]
        assert consumer2.returncode == 0, consumer2.stderr[-2000:]
        assert f"resuming at event >= {resume_at}" in p_out, p_out[-1500:]
    finally:
        server2.kill()
        server2.wait(timeout=15)

    done2 = _processed_indices(consumer2.stdout + consumer2.stderr)
    # at-least-once, no gap: the union covers every event exactly
    assert done1 | done2 == set(range(N_EVENTS)), (
        f"gap: missing {sorted(set(range(N_EVENTS)) - (done1 | done2))[:10]}"
    )
    # run 2 really resumed (started from the watermark, not from zero)
    assert min(done2) == resume_at
    # and the final cursor covers the whole stream
    with open(cursor) as f:
        assert int(json.load(f)["positions"]["0"]) == N_EVENTS - 1
