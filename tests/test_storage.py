"""Segment-log storage unit tests (ISSUE 8): record framing + CRC
recovery, rollover/recycle, committed offsets, DurableRingBuffer
contract (spill, ack floor, put_front reinstatement, restart
re-exposure), and the replay cursor."""

import glob
import os

import numpy as np
import pytest

from psana_ray_tpu.records import EndOfStream, FrameRecord
from psana_ray_tpu.storage import (
    REPLAY_BEGIN,
    REPLAY_RESUME,
    DurableRingBuffer,
    SegmentLog,
)
from psana_ray_tpu.transport.ring import EMPTY


def _rec(i, value=None, shape=(1, 8, 8)):
    return FrameRecord(
        0, i, np.full(shape, i if value is None else value, np.uint16), 9.5
    )


def _log(tmp_path, **kw):
    kw.setdefault("segment_bytes", 1 << 20)
    kw.setdefault("fsync", "none")
    return SegmentLog(str(tmp_path / "log"), name="t", **kw)


class TestSegmentLog:
    def test_append_read_round_trip_all_payload_kinds(self, tmp_path):
        log = _log(tmp_path)
        o0 = log.append(_rec(7))
        o1 = log.append(EndOfStream(total_events=7, producer_rank=3))
        o2 = log.append({"arbitrary": "pickle"})
        assert (o0, o1, o2) == (0, 1, 2)
        back = log.read(o0)
        assert back.equals(_rec(7)) and back.panels.dtype == np.uint16
        eos = log.read(o1)
        assert isinstance(eos, EndOfStream) and eos.producer_rank == 3
        assert log.read(o2) == {"arbitrary": "pickle"}
        log.close()

    def test_offsets_survive_reopen(self, tmp_path):
        log = _log(tmp_path)
        for i in range(5):
            log.append(_rec(i))
        log.commit(2, "")
        log.commit(4, "model-v2")
        log.close()
        log2 = _log(tmp_path)
        assert log2.next_offset == 5
        assert log2.committed("") == 2
        assert log2.committed("model-v2") == 4
        assert log2.read(3).event_idx == 3
        log2.close()

    def test_commit_is_monotonic(self, tmp_path):
        log = _log(tmp_path)
        log.append(_rec(0))
        assert log.commit(0, "g") is True
        assert log.commit(0, "g") is False  # no regress, no rewrite
        log.close()

    def test_rollover_and_recycle_bound_disk(self, tmp_path):
        log = _log(tmp_path, segment_bytes=4096, retain_segments=2)
        q = DurableRingBuffer(log, maxsize=500, ram_items=8, name="t")
        for i in range(100):
            assert q.put(_rec(i))
        assert log.stats()["segments"] > 3  # really rolled
        out = q.get_batch(200, timeout=0)
        assert len(out) == 100
        q.ack_delivered(out)
        s = log.stats()
        # retention: at most retain+1 live segments of consumed history
        assert s["segments"] <= 3
        assert s["first_retained_offset"] > 0  # history really recycled
        # recycled segments sit on the free list OUT of the seg namespace
        free = glob.glob(str(tmp_path / "log" / "free-*.seg"))
        assert len(free) == s["free_segments"] <= 2
        log.close()

    def test_torn_tail_truncated_and_flagged(self, tmp_path):
        log = _log(tmp_path)
        for i in range(6):
            log.append(_rec(i))
        seg = log._segments[-1]
        victim_pos = seg.find(5)
        path = seg.path
        log.close()
        with open(path, "r+b") as f:  # corrupt the LAST record's payload
            f.seek(victim_pos + 24)
            f.write(b"\xde\xad\xbe\xef")
        log2 = _log(tmp_path)
        assert log2.torn_tail_repaired is True
        assert log2.next_offset == 5  # truncated to the last valid record
        assert log2.read(4).event_idx == 4
        # the repaired region appends cleanly again
        assert log2.append(_rec(50)) == 5
        assert log2.read(5).event_idx == 50
        log2.close()

    def test_free_segment_leftovers_ignored_on_boot(self, tmp_path):
        log = _log(tmp_path)
        log.append(_rec(0))
        log.close()
        # a crash can leave retired free-* files around: they must never
        # scan as history
        open(str(tmp_path / "log" / "free-9.seg"), "wb").write(b"\x01" * 64)
        log2 = _log(tmp_path)
        assert log2.next_offset == 1
        assert not os.path.exists(str(tmp_path / "log" / "free-9.seg"))
        log2.close()

    def test_oversized_record_fails_fast(self, tmp_path):
        log = _log(tmp_path, segment_bytes=4096)
        with pytest.raises(ValueError, match="segment_bytes"):
            log.append(_rec(0, shape=(4, 64, 64)))  # 32 KB > 4 KB segment
        log.close()

    def test_offset_store_compacts(self, tmp_path):
        log = _log(tmp_path)
        log.append(_rec(0))
        for i in range(3000):  # enough lines to cross the threshold
            log.commit(i, f"g{i % 7}")
        path = str(tmp_path / "log" / "offsets.jsonl")
        assert os.path.getsize(path) < 128 * 1024
        log.close()
        log2 = _log(tmp_path)
        assert log2.committed("g0") == 2996
        log2.close()


class TestDurableRingBuffer:
    def test_contract_parity_with_ringbuffer(self, tmp_path):
        q = DurableRingBuffer(_log(tmp_path), maxsize=2, name="t")
        assert q.get() is EMPTY
        assert q.put(_rec(0)) and q.put(_rec(1))
        assert q.put(_rec(2)) is False  # full, rejected, NOT logged
        assert q.log.next_offset == 2
        assert q.get().event_idx == 0
        assert q.size() == 1
        stats = q.stats()
        assert stats["durable"] is True and stats["puts"] == 2

    def test_spill_beyond_ram_bounded_depth(self, tmp_path):
        q = DurableRingBuffer(
            _log(tmp_path), maxsize=64, ram_items=4, name="t"
        )
        for i in range(40):
            assert q.put(_rec(i))
        st = q.stats()
        assert st["resident"] == 4 and st["spilled"] == 36
        out = q.get_batch(64, timeout=0)
        assert [r.event_idx for r in out] == list(range(40))
        # spilled records decode to full-fidelity owned copies
        assert np.array_equal(out[20].panels, _rec(20).panels)
        assert q.stats()["spilled"] == 0

    def test_ack_floor_advances_only_over_acked_prefix(self, tmp_path):
        q = DurableRingBuffer(_log(tmp_path), maxsize=16, name="t")
        for i in range(6):
            q.put(_rec(i))
        a, b, c = q.get(), q.get(), q.get()
        q.ack_delivered([b])  # out-of-order ack: floor must NOT move
        assert q.stats()["committed_offset"] == -1
        q.ack_delivered([a])
        assert q.stats()["committed_offset"] == 1  # a+b contiguous now
        q.ack_delivered([c])
        assert q.stats()["committed_offset"] == 2

    def test_put_front_reinstates_original_offset(self, tmp_path):
        q = DurableRingBuffer(_log(tmp_path), maxsize=16, name="t")
        q.put(_rec(0))
        q.put(_rec(1))
        x = q.get()
        logged = q.log.next_offset
        q.put_front(x)  # crash-redelivery path: NO duplicate append
        assert q.log.next_offset == logged
        y = q.get()
        assert y.event_idx == 0
        q.ack_delivered([y])
        assert q.stats()["committed_offset"] == 0

    def test_restart_reexposes_unconsumed_range(self, tmp_path):
        q = DurableRingBuffer(_log(tmp_path), maxsize=32, name="t")
        for i in range(10):
            q.put(_rec(i))
        q.put(EndOfStream(total_events=10))
        got = q.get_batch(4, timeout=0)
        q.ack_delivered(got)
        delivered_unacked = q.get_batch(2, timeout=0)  # popped, NEVER acked
        assert [r.event_idx for r in delivered_unacked] == [4, 5]
        q.log.close()  # crash: nothing graceful beyond page cache
        q2 = DurableRingBuffer(_log(tmp_path), maxsize=32, name="t")
        rest = q2.get_batch(32, timeout=0)
        idxs = [getattr(r, "event_idx", "EOS") for r in rest]
        # rewind to committed offset: the unacked 4,5 REDELIVER (dupes
        # possible), 6..9 + EOS arrive, nothing lost
        assert idxs == [4, 5, 6, 7, 8, 9, "EOS"]
        q2.log.close()

    def test_commit_on_get_mode(self, tmp_path):
        q = DurableRingBuffer(
            _log(tmp_path), maxsize=8, name="t", commit_on_get=True
        )
        q.put(_rec(0))
        q.put(_rec(1))
        q.get()
        assert q.stats()["committed_offset"] == 0
        assert q.stats()["outstanding"] == 0  # nothing tracked

    def test_replay_cursor_begin_and_resume(self, tmp_path):
        q = DurableRingBuffer(_log(tmp_path), maxsize=32, name="t")
        for i in range(8):
            q.put(_rec(i))
        live = q.get_batch(8, timeout=0)
        q.ack_delivered(live)  # live consumption complete
        cur = q.open_replay("model-v2", REPLAY_BEGIN)
        first = cur.next_batch(3)
        assert [r.event_idx for r in first] == [0, 1, 2]
        assert cur.commit() is True
        # resume continues after the committed position
        cur2 = q.open_replay("model-v2", REPLAY_RESUME)
        rest = cur2.next_batch(32)
        assert [r.event_idx for r in rest] == [3, 4, 5, 6, 7]
        assert cur2.caught_up()
        # a second group is independent
        cur3 = q.open_replay("model-v3", REPLAY_RESUME)
        assert [r.event_idx for r in cur3.next_batch(2)] == [0, 1]

    def test_heartbeat_suffix_surfaces_durability_breadcrumbs(self, tmp_path):
        from psana_ray_tpu.obs.tracing import obs_status_suffix

        log = _log(tmp_path, segment_bytes=4096)
        q = DurableRingBuffer(log, maxsize=200, ram_items=2, name="t")
        for i in range(20):  # forces rollovers AND spill
            q.put(_rec(i))
        suffix = obs_status_suffix()
        assert "durable[" in suffix
        assert "roll=" in suffix and "spill=" in suffix and "torn=" in suffix
        log.close()

    def test_replay_does_not_disturb_live_queue(self, tmp_path):
        q = DurableRingBuffer(_log(tmp_path), maxsize=32, name="t")
        for i in range(5):
            q.put(_rec(i))
        cur = q.open_replay("g", REPLAY_BEGIN)
        assert len(cur.next_batch(100)) == 5
        assert q.size() == 5  # live depth untouched
        assert [r.event_idx for r in q.get_batch(8, timeout=0)] == list(range(5))
