"""Zero-copy host datapath (ISSUE 2): wire codec buffer-protocol edge
cases, scatter-gather equivalence, pool lease discipline, and the
copy-count pin — consumer-side copies/frame on the TCP path is EXACTLY
one (the batch-arena memcpy), with steady-state recv allocations zero.
"""

import threading

import numpy as np
import pytest

from psana_ray_tpu.infeed.batcher import FrameBatcher, batches_from_queue
from psana_ray_tpu.records import EndOfStream, FrameRecord, decode
from psana_ray_tpu.transport.codec import (
    decode_payload,
    encode_payload,
    encode_payload_parts,
    payload_nbytes,
)
from psana_ray_tpu.transport.ring import RingBuffer
from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer
from psana_ray_tpu.utils.bufpool import WIRE, BufferPool


def _rec(idx=0, shape=(2, 4, 8), dtype=np.float32, rank=1, energy=9.5):
    panels = np.arange(int(np.prod(shape)), dtype=dtype).reshape(shape) + idx
    return FrameRecord(rank, idx, panels, energy, timestamp=1.25)


class TestWirePartsEquivalence:
    """wire_parts() must be byte-for-byte to_bytes() — the scatter-gather
    sender and any legacy contiguous consumer read the same stream."""

    def test_contiguous_roundtrip(self):
        rec = _rec()
        header, payload = rec.wire_parts()
        assert isinstance(payload, memoryview)
        assert header + payload.tobytes() == rec.to_bytes()
        assert decode(rec.to_bytes()).equals(rec)

    def test_zero_copy_payload_is_a_view(self):
        rec = _rec()
        _, payload = rec.wire_parts()
        # same memory, not a copy: writing through the record shows in
        # the payload view (contiguous panels only)
        base = np.frombuffer(payload, dtype=rec.panels.dtype)
        assert base[0] == rec.panels.ravel()[0]
        assert np.shares_memory(np.asarray(rec.panels), base)

    @pytest.mark.parametrize("dtype", [np.uint16, np.float64, np.int16, np.uint8])
    def test_dtype_shape_roundtrip(self, dtype):
        rec = _rec(shape=(3, 5, 7), dtype=dtype)
        header, payload = rec.wire_parts()
        out = decode(header + payload.tobytes())
        assert out.equals(rec)
        assert out.panels.dtype == np.dtype(dtype)
        assert out.panels.shape == (3, 5, 7)

    def test_non_contiguous_panels(self):
        # strided slice: wire_parts must emit the contiguous content
        full = np.arange(2 * 4 * 12, dtype=np.float32).reshape(2, 4, 12)
        rec = FrameRecord(0, 3, full[:, :, ::3], 7.5)
        assert not rec.panels.flags.c_contiguous
        header, payload = rec.wire_parts()
        assert header + payload.tobytes() == rec.to_bytes()
        assert decode(rec.to_bytes()).equals(rec)

    def test_encode_parts_matches_encode_payload(self):
        for item in (_rec(), EndOfStream(producer_rank=2, total_events=5), {"x": 1}):
            parts = encode_payload_parts(item)
            flat = b"".join(bytes(p) for p in parts)
            assert flat == encode_payload(item)
            assert payload_nbytes(parts) == len(flat)


class TestLeasedDecode:
    def test_decode_view_into_pooled_buffer(self):
        pool = BufferPool()
        rec = _rec(shape=(2, 8, 8))
        wire = rec.to_bytes()
        lease = pool.lease(len(wire))
        lease.mv[:] = wire
        out = decode(lease.mv, lease=lease)
        assert out.equals(rec)
        assert out.lease is lease
        # zero-copy: the panels view the pooled buffer
        assert np.shares_memory(
            np.asarray(out.panels), np.frombuffer(lease.mv, dtype=np.uint8)
        )
        assert pool.stats()["leases"] == 1
        out.release()
        assert out.lease is None
        assert pool.stats()["leases"] == 0
        out.release()  # idempotent

    def test_memoryview_slice_of_pooled_buffer(self):
        # tagged-payload form: decode_payload sees a SLICE of the lease
        pool = BufferPool()
        rec = _rec(shape=(1, 4, 4), dtype=np.uint16)
        payload = encode_payload(rec)
        lease = pool.lease(len(payload))
        lease.mv[:] = payload
        out = decode_payload(lease.mv, lease=lease)
        assert out.equals(rec) and out.lease is lease
        out.release()
        assert pool.stats()["leases"] == 0

    def test_non_record_payload_releases_lease_after_parse(self):
        pool = BufferPool()
        payload = encode_payload({"k": list(range(100))})
        lease = pool.lease(len(payload))
        lease.mv[:] = payload
        out = decode_payload(lease.mv, lease=lease)
        assert out == {"k": list(range(100))}
        assert pool.stats()["leases"] == 0

    def test_gc_releases_dropped_record(self):
        pool = BufferPool()
        rec = _rec()
        lease = pool.lease(len(rec.to_bytes()))
        lease.mv[:] = rec.to_bytes()
        out = decode(lease.mv, lease=lease)
        del lease
        assert pool.stats()["leases"] == 1
        del out  # CPython refcount drop -> Lease.__del__ -> release
        assert pool.stats()["leases"] == 0

    def test_materialize_detaches_from_lease(self):
        pool = BufferPool()
        rec = _rec(shape=(2, 4, 4))
        lease = pool.lease(len(rec.to_bytes()))
        lease.mv[:] = rec.to_bytes()
        out = decode(lease.mv, lease=lease)
        owned = out.materialize()
        assert pool.stats()["leases"] == 0  # released by materialize
        assert owned.lease is None and owned.equals(rec)
        # buffer reuse cannot corrupt the materialized copy
        lease2 = pool.lease(len(rec.to_bytes()))
        lease2.mv[:] = b"\xff" * len(lease2.mv)
        assert owned.equals(rec)
        lease2.release()

    def test_push_view_releases_after_copy(self):
        pool = BufferPool()
        batcher = FrameBatcher(batch_size=2)
        recs = [_rec(i) for i in range(2)]
        for i, r in enumerate(recs):
            wire = r.to_bytes()
            lease = pool.lease(len(wire))
            lease.mv[:] = wire
            view = decode(lease.mv, lease=lease)
            out = batcher.push_view(view)
            assert pool.stats()["leases"] == 0  # released right after copy
        assert out is not None
        np.testing.assert_array_equal(out.frames[0], recs[0].panels)
        np.testing.assert_array_equal(out.frames[1], recs[1].panels)


class TestBufferPool:
    def test_hit_after_release(self):
        pool = BufferPool()
        a = pool.lease(1000)
        a.release()
        b = pool.lease(900)  # same 4 KB class
        s = pool.stats()
        assert s["hits"] == 1 and s["misses"] == 1
        b.release()

    def test_adaptive_retention_tracks_peak(self):
        pool = BufferPool(min_per_class=1)
        burst = [pool.lease(5000) for _ in range(8)]
        for le in burst:
            le.release()
        # all 8 existed concurrently: all are retained and re-leasable
        again = [pool.lease(5000) for _ in range(8)]
        assert pool.stats()["misses"] == 8  # only the initial burst
        assert pool.stats()["hits"] == 8
        for le in again:
            le.release()

    def test_retention_decays_after_burst(self):
        # a one-time burst must not pin its high-water of memory forever:
        # the per-class peak decays toward the live working set
        pool = BufferPool(min_per_class=1)
        burst = [pool.lease(5000) for _ in range(8)]
        for le in burst:
            le.release()
        assert pool.stats()["bytes_pooled"] == 8 * 8192
        for _ in range(pool.DECAY_EVERY * 8):  # steady state: 1 at a time
            pool.lease(5000).release()
        assert pool.stats()["bytes_pooled"] <= 2 * 8192

    def test_oversized_wire_length_rejected(self):
        # a corrupt/hostile u32 length must not size a pool lease
        import socket as socket_mod

        from psana_ray_tpu.transport.tcp import _MAX_PAYLOAD, _recv_payload

        a, b = socket_mod.socketpair()
        try:
            with pytest.raises(ConnectionError, match="wire maximum"):
                _recv_payload(a, _MAX_PAYLOAD + 1, BufferPool())
        finally:
            a.close()
            b.close()

    def test_leak_tracking_in_debug_mode(self):
        pool = BufferPool(debug=True)
        lease = pool.lease(64)
        assert len(pool.leaks()) == 1
        lease.release()
        assert pool.leaks() == []


class TestTcpCopyCount:
    """THE acceptance pin: over a real TCP server, consumer-side
    copies/frame == 1 (the batch-arena memcpy) and steady-state recv
    allocations come from the pool, not malloc — on BOTH drain modes
    (request/response pull and the ISSUE 5 server-push stream the
    batcher now prefers)."""

    def _run_relay(self, n, prefer_stream, pool=None, codec=None, shape=(2, 16, 16)):
        q = RingBuffer(16)
        srv = TcpQueueServer(q, host="127.0.0.1", pool=pool).serve_background()
        prod = TcpQueueClient("127.0.0.1", srv.port, pool=pool, codec=codec)
        cons = TcpQueueClient("127.0.0.1", srv.port, pool=pool, codec=codec)
        try:

            def produce():
                for i in range(n):
                    assert prod.put_wait(_rec(i, shape=shape), timeout=30)
                assert prod.put_wait(EndOfStream(total_events=n), timeout=30)

            t = threading.Thread(target=produce, daemon=True)
            c0 = WIRE.stats()
            t.start()
            seen = 0
            for batch in batches_from_queue(
                cons, 8, poll_interval_s=0.002, prefer_stream=prefer_stream
            ):
                seen += batch.num_valid
            t.join()
            assert seen == n
            if prefer_stream:
                assert cons._stream is not None  # the drain actually streamed
            d = WIRE.stats()
            return (
                d["copies_total"] - c0["copies_total"],
                d["bytes_copied_total"] - c0["bytes_copied_total"],
            )
        finally:
            prod.disconnect()
            cons.disconnect()
            srv.shutdown()
            # at-least-once tail: if the server processes the stream
            # conn's death before the disconnect's final cumulative ack
            # (a race a CPU-starved box widens), the tail frames requeue
            # — RETAINED by the queue for redelivery, not leaked. After
            # shutdown every requeue has landed; hand those leases back
            # so the zero-leak pins below measure leaks, not the
            # redelivery guarantee.
            from psana_ray_tpu.transport.ring import EMPTY as _EMPTY

            while True:
                item = q.get()
                if item is _EMPTY:
                    break
                release = getattr(item, "release", None)
                if release is not None:
                    release()

    def test_consumer_side_exactly_one_copy_per_frame(self):
        n = 24
        copies, nbytes = self._run_relay(n, prefer_stream=False)
        assert copies == n, f"expected exactly 1 copy/frame, got {copies}/{n}"
        assert nbytes == n * _rec(0, shape=(2, 16, 16)).nbytes

    def test_streaming_drain_exactly_one_copy_zero_alloc_per_frame(self):
        """ISSUE 5 acceptance: the server-push stream preserves the
        zero-copy discipline — copies/frame == 1.00 AND zero pool-churn
        allocations (every recv lease recycled; working-set growth up to
        the credit window is not churn), measured on an instrumented
        private pool."""
        from psana_ray_tpu.utils.bufpool import BufferPool

        pool = BufferPool()
        n = 24
        copies, nbytes = self._run_relay(n, prefer_stream=True, pool=pool)
        assert copies == n, f"expected exactly 1 copy/frame, got {copies}/{n}"
        assert nbytes == n * _rec(0, shape=(2, 16, 16)).nbytes
        s = pool.stats()
        assert s["churn_misses"] == 0, (
            f"streaming path churned {s['churn_misses']} allocations "
            f"(pool: {s})"
        )
        # the last pushed window stays leased until the client's final
        # cumulative ack (sent at disconnect) prunes it server-side —
        # that retention IS the redelivery guarantee, so allow the
        # asynchronous prune a moment before calling anything a leak
        # (10 s: under a CPU-share-throttled full tier-1 run the prune
        # + record GC episodically exceeded the old 2 s grace — a leak
        # never clears however long we wait, so the wider window only
        # trades flake for patience)
        import time as _time

        deadline = _time.monotonic() + 10.0
        while pool.stats()["leases"] and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert pool.stats()["leases"] == 0, (
            f"leaked leases after drain+ack: {pool.stats()}"
        )

    def test_compressed_streaming_one_copy_zero_alloc_zero_leaks(self):
        """ISSUE 9 acceptance pin: the NEGOTIATED-CODEC streaming path
        keeps the zero-copy discipline — copies/frame == 1.00 (the
        batch-arena memcpy; compress/decompress stage through pool
        leases, never fresh allocations or extra payload memcpys),
        steady-state pool churn == 0, and zero leaked leases after the
        drain's final ack (compressed staging + pass-through cache +
        decompressed-panel leases all recycle)."""
        from psana_ray_tpu.transport.codec import CODEC_STATS

        pool = BufferPool()
        n = 24
        # big enough to clear WIRE_COMPRESS_MIN — the pin must exercise
        # the codec, not the too-small passthrough
        shape = (2, 32, 32)
        s0 = CODEC_STATS.stats()
        copies, nbytes = self._run_relay(
            n, prefer_stream=True, pool=pool, codec="shuffle-rle", shape=shape
        )
        s1 = CODEC_STATS.stats()
        # the pin only means something if the codec actually engaged
        assert s1["frames_compressed_total"] > s0["frames_compressed_total"]
        assert copies == n, f"expected exactly 1 copy/frame, got {copies}/{n}"
        assert nbytes == n * _rec(0, shape=shape).nbytes
        s = pool.stats()
        assert s["churn_misses"] == 0, (
            f"compressed streaming churned {s['churn_misses']} allocations "
            f"(pool: {s})"
        )
        import time as _time

        deadline = _time.monotonic() + 10.0
        while pool.stats()["leases"] and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert pool.stats()["leases"] == 0, (
            f"leaked leases after compressed drain+ack: {pool.stats()}"
        )

    def test_tcp_roundtrip_content_through_pool(self):
        # recycled buffers must never bleed between frames
        srv = TcpQueueServer(RingBuffer(4), host="127.0.0.1").serve_background()
        c = TcpQueueClient("127.0.0.1", srv.port)
        try:
            for i in range(12):
                rec = _rec(i, shape=(1, 32, 32), dtype=np.uint16)
                assert c.put(rec)
                out = c.get()
                assert out.equals(rec), f"frame {i} corrupted through pooled path"
        finally:
            c.disconnect()
            srv.shutdown()
