"""Tests for ISSUE 4: sampled per-frame distributed tracing.

Covers the satellite test checklist: trace-context wire round-trips over
TCP and shm (sampled AND unsampled — the unsampled wire stays
byte-identical v2), the zero-allocation pin on the unsampled hot path,
the clock-anchor RPC, span emission through the batching pipeline, and
the trace_merge golden-output test (3 handcrafted spools with known
monotonic offsets -> one valid Chrome trace-event JSON)."""

from __future__ import annotations

import gc
import json
import os
import sys

import numpy as np
import pytest

from psana_ray_tpu.obs.tracing import (
    TRACE_KEY,
    TRACER,
    TraceContext,
    Tracer,
    emit_batch_spans,
    exchange_anchors,
)
from psana_ray_tpu.records import FrameRecord, decode, encode_into, encoded_size


@pytest.fixture
def tracer(tmp_path):
    t = Tracer()
    t.configure(str(tmp_path), sample_every=1, process="test")
    yield t
    t.close()


@pytest.fixture(autouse=True)
def _global_tracer_off():
    yield
    TRACER.close()


def _frame(trace=None, shape=(2, 8, 8)):
    return FrameRecord(
        0, 7, np.arange(np.prod(shape), dtype=np.uint16).reshape(shape),
        9.5, timestamp=123.5, trace=trace,
    )


CTX = TraceContext(trace_id=0x1234_5678_9ABC, origin_host="hosta", origin_pid=4242)


class TestContextWireFormat:
    def test_pack_unpack_round_trip(self):
        buf = CTX.pack()
        assert len(buf) == TraceContext.WIRE_SIZE == 25
        out = TraceContext.unpack_from(buf, 0)
        assert out == CTX

    def test_long_hostname_truncates_not_raises(self):
        ctx = TraceContext(1, True, "a-very-long-hostname.example.com", 1)
        out = TraceContext.unpack_from(ctx.pack(), 0)
        assert out.origin_host == "a-very-long-"  # 12-byte budget

    def test_sampled_frame_encodes_v3_with_context(self):
        rec = _frame(trace=CTX)
        out = FrameRecord.from_bytes(rec.to_bytes())
        assert out.schema_version == 3
        assert out.trace == CTX
        assert out.equals(rec)

    def test_unsampled_frame_encodes_v2_byte_identical(self):
        # THE zero-cost contract: no trace context -> the wire bytes are
        # exactly the pre-tracing v2 format (no extra bytes, no version
        # bump), so unsampled streams are indistinguishable from before
        rec = _frame()
        wire = rec.to_bytes()
        out = FrameRecord.from_bytes(wire)
        assert out.schema_version == 2 and out.trace is None
        assert encoded_size(rec) == len(wire)
        traced = _frame(trace=CTX)
        assert encoded_size(traced) == len(wire) + TraceContext.WIRE_SIZE

    def test_encode_into_matches_to_bytes_both_ways(self):
        for rec in (_frame(), _frame(trace=CTX)):
            buf = bytearray(encoded_size(rec))
            n = encode_into(rec, buf)
            assert n == len(buf) and buf == rec.to_bytes()
            out = decode(memoryview(buf))
            assert out.trace == rec.trace

    def test_materialize_carries_trace(self):
        rec = _frame(trace=CTX)
        assert rec.materialize().trace == CTX


class TestTcpRoundTrip:
    def test_sampled_and_unsampled_over_tcp(self):
        from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer

        srv = TcpQueueServer(host="127.0.0.1").serve_background()
        c = TcpQueueClient("127.0.0.1", srv.port)
        try:
            assert c.put(_frame(trace=CTX))
            assert c.put(_frame())
            a, b = c.get(), c.get()
            assert a.trace == CTX and a.equals(_frame(trace=CTX))
            assert b.trace is None
        finally:
            c.disconnect()
            srv.shutdown()

    def test_anchor_rpc(self):
        from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer

        srv = TcpQueueServer(host="127.0.0.1").serve_background()
        c = TcpQueueClient("127.0.0.1", srv.port)
        try:
            a = c.anchor()
            assert a["rtt_s"] >= 0
            assert a["send_mono"] <= a["recv_mono"]
            assert a["peer_wall"] > 0 and a["peer_mono"] > 0
        finally:
            c.disconnect()
            srv.shutdown()

    def test_exchange_anchors_spools_peer_records(self, tmp_path):
        from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer

        t = Tracer().configure(str(tmp_path), sample_every=1, process="c")
        srv = TcpQueueServer(host="127.0.0.1").serve_background()
        c = TcpQueueClient("127.0.0.1", srv.port)
        try:
            assert exchange_anchors(c, n=2, tracer=t) == 2
        finally:
            c.disconnect()
            srv.shutdown()
        t.close()
        lines = [json.loads(s) for s in open(t.spool_path) if s.strip()]
        assert sum(1 for r in lines if r["t"] == "p") == 2

    def test_exchange_anchors_noop_without_rpc(self, tracer):
        class Plain:
            pass

        assert exchange_anchors(Plain(), tracer=tracer) == 0


class TestShmRoundTrip:
    @pytest.fixture
    def ring(self):
        from psana_ray_tpu.transport.shm_ring import ShmRingBuffer, native_available

        if not native_available():
            pytest.skip("native shm ring unavailable")
        r = ShmRingBuffer.create(f"trace_rt_{os.getpid()}", maxsize=4)
        yield r
        r.destroy()

    def test_sampled_and_unsampled_over_shm(self, ring):
        assert ring.put(_frame(trace=CTX))
        assert ring.put(_frame())
        a, b = ring.get(), ring.get()
        assert a.trace == CTX and a.equals(_frame(trace=CTX))
        assert b.trace is None

    def test_zero_copy_view_keeps_trace(self, ring):
        assert ring.put(_frame(trace=CTX))
        rec = ring.get_view()
        try:
            assert rec.trace == CTX
        finally:
            rec.release()


class TestSamplingGate:
    def test_disabled_returns_none(self):
        assert Tracer().maybe_trace() is None

    def test_sample_every_n(self, tmp_path):
        t = Tracer().configure(str(tmp_path), sample_every=4, process="p")
        got = [t.maybe_trace() for _ in range(16)]
        assert sum(c is not None for c in got) == 4
        ids = [c.trace_id for c in got if c is not None]
        assert len(set(ids)) == 4  # unique per sampled frame
        t.close()

    def test_unsampled_path_is_allocation_free(self, tmp_path):
        """The zero-alloc pin: with tracing ENABLED, frames that miss the
        sample gate cost counter arithmetic only — no net allocations
        (the PR 1 stage_timing discipline, now pinned for tracing)."""
        t = Tracer().configure(str(tmp_path), sample_every=10_000_000, process="p")
        try:
            for _ in range(64):
                t.maybe_trace()  # warm any int caching
            gc.disable()
            try:
                gc.collect()
                before = sys.getallocatedblocks()
                for _ in range(10_000):
                    t.maybe_trace()
                after = sys.getallocatedblocks()
            finally:
                gc.enable()
            # a handful of blocks of allocator/freelist noise is fine; a
            # real per-frame allocation would show >= 10_000 blocks here
            assert after - before <= 16, (
                f"unsampled maybe_trace leaked {after - before} blocks "
                f"over 10k frames"
            )
        finally:
            t.close()

    def test_disabled_tracer_span_is_noop(self):
        t = Tracer()
        t.span(1, "x", 0.0, 1.0)  # must not raise, must not spool
        t.instant(1, "y", 0.0)
        assert t.snapshot()["spans_total"] == 0


class TestSpool:
    def test_spool_contains_meta_anchor_span(self, tmp_path):
        t = Tracer().configure(str(tmp_path), sample_every=1, process="prod")
        ctx = t.maybe_trace()
        t.span(ctx.trace_id, "enqueue", 1.0, 2.0)
        t.instant(ctx.trace_id, "produce", 1.0)
        t.close()
        lines = [json.loads(s) for s in open(t.spool_path) if s.strip()]
        kinds = [r["t"] for r in lines]
        assert kinds.count("m") == 1 and "a" in kinds
        spans = [r for r in lines if r["t"] == "s"]
        assert spans == [{"t": "s", "id": ctx.trace_id, "n": "enqueue", "a": 1.0, "b": 2.0}]
        meta = next(r for r in lines if r["t"] == "m")
        assert meta["process"] == "prod" and meta["every"] == 1

    def test_bounded_spool_drops_and_counts(self, tmp_path):
        t = Tracer().configure(str(tmp_path), sample_every=1, process="p", max_spans=3)
        for i in range(10):
            t.span(i, "s", 0.0, 1.0)
        snap = t.snapshot()
        t.close()
        assert snap["spans_total"] == 3 and snap["spans_dropped_total"] == 7

    def test_status_suffix_shows_rate_spans_flight(self, tmp_path):
        from psana_ray_tpu.obs.flight import FlightRecorder

        t = Tracer()
        assert t.status_suffix() == ""  # off: heartbeat line unchanged
        t.configure(str(tmp_path), sample_every=100, process="p")
        t.span(1, "s", 0.0, 1.0)
        fl = FlightRecorder()
        fl.record("eos_complete")
        suffix = t.status_suffix(fl)
        t.close()
        assert "trace[1/100 spans=1]" in suffix and "flight=1" in suffix


class TestBatchPathSpans:
    def test_batches_from_queue_stamps_traced_records(self, tracer, monkeypatch):
        import psana_ray_tpu.infeed.batcher as batcher_mod
        from psana_ray_tpu.infeed.batcher import batches_from_queue
        from psana_ray_tpu.records import EndOfStream
        from psana_ray_tpu.transport.ring import RingBuffer

        monkeypatch.setattr(batcher_mod, "TRACER", tracer)
        q = RingBuffer(16)
        ctx = tracer.maybe_trace()
        for i in range(3):
            q.put(_frame(trace=ctx if i == 0 else None))
        q.put(EndOfStream(total_events=3))
        batches = list(batches_from_queue(q, 3))
        assert len(batches) == 1
        hops = batches[0].hops
        assert hops is not None and len(hops) == 1  # only the traced record
        assert hops[0][TRACE_KEY] == ctx.trace_id

    def test_emit_batch_spans_telescopes_hops(self, tracer):
        from psana_ray_tpu.obs.stages import HOP_BATCH, HOP_DEQ, HOP_PUSH

        class B:
            hops = [{TRACE_KEY: 99, HOP_DEQ: 1.0, HOP_PUSH: 2.0, HOP_BATCH: 3.0}]

        emit_batch_spans(B(), 4.0, tracer=tracer)
        tracer.close()
        spans = [
            json.loads(s) for s in open(tracer.spool_path) if s.strip()
        ]
        spans = [(r["n"], r["a"], r["b"]) for r in spans if r["t"] == "s"]
        # deq->push = dequeue, push->batch = batch, batch->t_end = dispatch
        assert spans == [
            ("dequeue", 1.0, 2.0), ("batch", 2.0, 3.0), ("dispatch", 3.0, 4.0),
        ]

    def test_no_duplicate_enqueue_span_in_process(self, tracer):
        # in-process transports share the hops dict with the producer,
        # whose _Sender.flush already emitted the enqueue span — the
        # batch walk must not replay the src->enq leg (but keeps the
        # enq->deq queue_dwell no server exists to emit)
        from psana_ray_tpu.obs.stages import (
            HOP_BATCH, HOP_DEQ, HOP_ENQ, HOP_PUSH, HOP_SRC,
        )

        class B:
            hops = [{
                TRACE_KEY: 7, HOP_SRC: 1.0, HOP_ENQ: 2.0, HOP_DEQ: 3.0,
                HOP_PUSH: 4.0, HOP_BATCH: 5.0,
            }]

        emit_batch_spans(B(), 6.0, tracer=tracer)
        names = tracer.snapshot()["spans_by_name"]
        assert "enqueue" not in names, names
        assert names == {"queue_dwell": 1, "dequeue": 1, "batch": 1, "dispatch": 1}

    def test_untraced_batch_is_free(self, tracer):
        class B:
            hops = None

        emit_batch_spans(B(), 1.0, tracer=tracer)
        assert tracer.snapshot()["spans_total"] == 0


def _write_spool(path, process, host, pid, mono_offset, spans, peers=()):
    """A handcrafted spool whose monotonic clock is ``mono_offset`` behind
    wall time (offset = wall - mono)."""
    wall0 = 1_000_000.0
    lines = [
        {"t": "m", "process": process, "host": host, "pid": pid, "every": 1,
         "start_wall": wall0, "start_mono": wall0 - mono_offset},
        {"t": "a", "wall": wall0, "mono": wall0 - mono_offset},
        {"t": "a", "wall": wall0 + 1.0, "mono": wall0 + 1.0 - mono_offset},
    ]
    for p in peers:
        lines.append({"t": "p", **p})
    for tid, name, a, b in spans:
        lines.append({"t": "s", "id": tid, "n": name, "a": a, "b": b})
    with open(path, "w") as f:
        f.write("\n".join(json.dumps(ln) for ln in lines) + "\n")


class TestTraceMergeGolden:
    """3 spool files -> one valid Chrome trace JSON with the per-process
    monotonic offsets applied (the satellite golden-output test)."""

    def _make_spools(self, tmp_path):
        wall = 1_000_000.0
        tid = 0xABC
        # three processes, three DIFFERENT monotonic epochs: producer's
        # mono runs 100s behind wall, server's 200s, consumer's 300s —
        # the same frame's spans only order correctly if each offset is
        # applied per process
        _write_spool(
            tmp_path / "producer-h-1.trace.jsonl", "producer", "h", 1, 100.0,
            [(tid, "enqueue", wall - 100.0 + 0.10, wall - 100.0 + 0.20)],
        )
        _write_spool(
            tmp_path / "queue_server-h-2.trace.jsonl", "queue_server", "h", 2, 200.0,
            [
                (tid, "queue_dwell", wall - 200.0 + 0.25, wall - 200.0 + 0.40),
                (tid, "relay", wall - 200.0 + 0.40, wall - 200.0 + 0.45),
            ],
        )
        _write_spool(
            tmp_path / "consumer-h-3.trace.jsonl", "consumer", "h", 3, 300.0,
            [(tid, "dequeue", wall - 300.0 + 0.50, wall - 300.0 + 0.60)],
        )
        return tid, wall

    def test_merge_applies_offsets_and_links(self, tmp_path):
        from psana_ray_tpu.obs.trace_merge import merge

        tid, wall = self._make_spools(tmp_path)
        doc = merge([str(tmp_path)])
        json.dumps(doc)  # valid JSON document
        evts = doc["traceEvents"]
        names = {e["name"] for e in evts if e["ph"] == "M"}
        assert names == {"process_name"} and len(
            [e for e in evts if e["ph"] == "M"]
        ) == 3  # one track per process
        spans = sorted(
            (e for e in evts if e["ph"] == "X"), key=lambda e: e["ts"]
        )
        assert [s["name"] for s in spans] == [
            "enqueue", "queue_dwell", "relay", "dequeue",
        ]
        # offsets applied: all spans land on the shared wall timeline
        assert spans[0]["ts"] == pytest.approx((wall + 0.10) * 1e6, abs=1.0)
        assert spans[-1]["ts"] == pytest.approx((wall + 0.50) * 1e6, abs=1.0)
        # non-overlapping, monotone stage boundaries across processes
        for a, b in zip(spans, spans[1:]):
            assert a["ts"] + a["dur"] <= b["ts"] + 1e-6
        # linked by trace id, with a flow chain across the three tracks
        assert all(s["args"]["trace_id"] == f"{tid:#x}" for s in spans)
        flows = [e for e in evts if e["ph"] in ("s", "t", "f")]
        assert [f["ph"] for f in sorted(flows, key=lambda e: e["ts"])] == [
            "s", "t", "t", "f",
        ]
        assert {f["pid"] for f in flows} == {1, 2, 3}

    def test_peer_anchor_skew_correction(self, tmp_path):
        from psana_ray_tpu.obs.trace_merge import merge

        wall = 1_000_000.0
        # consumer's WALL clock runs 5s ahead of the server's; its peer
        # exchange reveals it: local wall mid = offset + mid_mono, server
        # replied peer_wall = local_est - 5
        mono_off = 300.0
        mid_mono = wall - mono_off + 0.5
        _write_spool(
            tmp_path / "queue_server-h-2.trace.jsonl", "queue_server", "h", 2, 200.0,
            [(1, "relay", wall - 200.0 + 0.40, wall - 200.0 + 0.45)],
        )
        _write_spool(
            tmp_path / "consumer-h-3.trace.jsonl", "consumer", "h", 3, mono_off,
            [(1, "dequeue", wall - mono_off + 0.50, wall - mono_off + 0.60)],
            peers=[{
                "send_wall": wall + 0.49, "send_mono": mid_mono - 0.01,
                "recv_wall": wall + 0.51, "recv_mono": mid_mono + 0.01,
                "peer_wall": (mono_off + mid_mono) - 5.0, "peer_mono": 0.0,
            }],
        )
        doc = merge([str(tmp_path)])
        track = next(
            t for t in doc["otherData"]["tracks"] if "consumer" in t["process"]
        )
        assert track["skew_vs_server_s"] == pytest.approx(5.0, abs=1e-6)
        spans = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
        # skew subtracted: ts = (mono + offset - skew) on the unified
        # (server-relative) timeline
        assert spans["dequeue"]["ts"] == pytest.approx(
            (wall + 0.50 - 5.0) * 1e6, abs=1.0
        )

    def test_cli_writes_valid_json(self, tmp_path):
        import subprocess

        self._make_spools(tmp_path)
        out = tmp_path / "merged.json"
        proc = subprocess.run(
            [sys.executable, "-m", "psana_ray_tpu.obs.trace_merge",
             str(tmp_path), "--out", str(out)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        doc = json.loads(out.read_text())
        assert doc["traceEvents"] and "3 process track(s)" in proc.stdout

    def test_no_spools_is_an_error(self, tmp_path):
        import subprocess

        proc = subprocess.run(
            [sys.executable, "-m", "psana_ray_tpu.obs.trace_merge",
             str(tmp_path)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 1 and "no trace spools" in proc.stderr

    def test_torn_tail_line_is_tolerated(self, tmp_path):
        from psana_ray_tpu.obs.trace_merge import load_spool

        p = tmp_path / "x-h-1.trace.jsonl"
        _write_spool(p, "x", "h", 1, 0.0, [(1, "s", 0.0, 1.0)])
        with open(p, "a") as f:
            f.write('{"t":"s","id":2,"n":"trunc')  # crashed mid-write
        spool = load_spool(str(p))
        assert len(spool["spans"]) == 1  # the torn line is skipped


class TestCliWiring:
    def test_shared_trace_flags(self):
        import argparse

        from psana_ray_tpu.obs.tracing import add_trace_args

        p = argparse.ArgumentParser()
        add_trace_args(p)
        a = p.parse_args(
            ["--trace_dir", "/tmp/t", "--trace_sample", "7", "--flight_dir", "/tmp/f"]
        )
        assert (a.trace_dir, a.trace_sample, a.flight_dir) == ("/tmp/t", 7, "/tmp/f")
        assert p.parse_args([]).trace_dir is None  # default off

    def test_configure_from_args_registers_sources(self, tmp_path):
        import argparse

        from psana_ray_tpu.obs.registry import MetricsRegistry
        from psana_ray_tpu.obs.tracing import add_trace_args, configure_from_args

        p = argparse.ArgumentParser()
        add_trace_args(p)
        a = p.parse_args(["--trace_dir", str(tmp_path), "--trace_sample", "3"])
        t = configure_from_args(a, "unit")
        try:
            assert t is TRACER and t.enabled and t.sample_every == 3
            names = MetricsRegistry.default().sources()
            assert "trace" in names and "flight" in names
        finally:
            from psana_ray_tpu.obs.flight import FLIGHT

            FLIGHT.uninstall()

    def test_consumer_heartbeat_appends_obs_suffix(self):
        # the heartbeat line includes sample rate / spans / flight count
        # (satellite: a live run shows tracing is actually on)
        import inspect

        import psana_ray_tpu.consumer as consumer_mod

        src = inspect.getsource(consumer_mod.main)
        assert "obs_status_suffix" in src and "--status_interval" in src

    def test_every_cli_takes_trace_flags(self):
        import inspect

        import psana_ray_tpu.consumer as c
        import psana_ray_tpu.producer as p
        import psana_ray_tpu.queue_server as q

        for mod, fn in ((c, c.main), (p, p.parse_arguments), (q, q.main)):
            assert "add_trace_args" in inspect.getsource(fn), mod.__name__
        # sfx too — source check only (importing psana_ray_tpu.sfx pulls jax)
        import pathlib

        sfx_src = (
            pathlib.Path(p.__file__).resolve().parent / "sfx.py"
        ).read_text()
        assert "add_trace_args" in sfx_src


class TestThreeProcessAcceptance:
    """The ISSUE 4 acceptance run: producer, queue server, and consumer
    as real processes with sampling on; the merged output must show at
    least one sampled frame with linked spans on all three tracks, with
    clock-aligned, non-overlapping stage boundaries."""

    def test_three_process_trace_merges_linked(self, tmp_path):
        import socket
        import subprocess

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        spool = tmp_path / "spool"

        def popen(mod, *args):
            return subprocess.Popen(
                [sys.executable, "-m", mod, *args],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )

        qs = popen(
            "psana_ray_tpu.queue_server", "--host", "127.0.0.1",
            "--port", str(port), "--queue_size", "32",
            "--trace_dir", str(spool), "--drain_s", "1",
        )
        cons = prod = None
        try:
            cons = popen(
                "psana_ray_tpu.consumer",
                "--address", f"tcp://127.0.0.1:{port}",
                "--queue_name", "shared_queue", "--max_frames", "32",
                "--quiet", "--trace_dir", str(spool), "--trace_sample", "4",
            )
            prod = popen(
                "psana_ray_tpu.producer", "--exp", "synthetic",
                "--detector_name", "smoke_a", "--num_events", "32",
                "--address", f"tcp://127.0.0.1:{port}",
                "--queue_name", "shared_queue",
                "--trace_dir", str(spool), "--trace_sample", "4",
            )
            pout, _ = prod.communicate(timeout=120)
            assert prod.returncode == 0, pout
            cout, _ = cons.communicate(timeout=120)
            assert cons.returncode == 0, cout
        finally:
            for p in (cons, prod):
                if p is not None and p.poll() is None:
                    p.kill()
            qs.terminate()
            qs.communicate(timeout=30)

        from psana_ray_tpu.obs.trace_merge import merge

        doc = merge([str(spool)])
        json.dumps(doc)  # valid
        tracks = doc["otherData"]["tracks"]
        assert len(tracks) == 3, tracks
        spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        by_trace: dict = {}
        for e in spans:
            by_trace.setdefault(e["args"]["trace_id"], []).append(e)
        linked = {
            tid: evs for tid, evs in by_trace.items()
            if len({e["pid"] for e in evs}) == 3
        }
        assert linked, f"no frame linked across all 3 tracks: {by_trace}"
        # clock-aligned, non-overlapping stage boundaries for a linked
        # frame — within the alignment error bound: cross-process span
        # placement is only as good as the anchor/skew estimate (~RTT),
        # so allow a few ms of slack instead of asserting exact ordering
        # (a 1 us bound here is tighter than the physics and flakes)
        SLACK_US = 5000.0
        evs = sorted(next(iter(linked.values())), key=lambda e: e["ts"])
        names = {e["name"] for e in evs}
        assert {"enqueue", "relay", "dequeue"} <= names, names
        for a, b in zip(evs, evs[1:]):
            assert a["ts"] + a["dur"] <= b["ts"] + SLACK_US, (a, b)
        # the producer's enqueue genuinely precedes the consumer's
        # dequeue END (read + processing) even under worst-case skew
        enq = min(e["ts"] for e in evs if e["name"] == "enqueue")
        deq_end = max(
            e["ts"] + e["dur"] for e in evs if e["name"] == "dequeue"
        )
        assert enq < deq_end + SLACK_US
