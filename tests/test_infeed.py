"""Infeed: fixed-shape batching, pad+mask tails, device prefetch, end-to-end
queue->mesh flow on the 8-device CPU mesh."""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psana_ray_tpu.infeed import DevicePrefetcher, FrameBatcher, InfeedPipeline
from psana_ray_tpu.infeed.batcher import batches_from_queue
from psana_ray_tpu.infeed.multihost import batch_sharding, make_global_batch
from psana_ray_tpu.parallel import create_mesh
from psana_ray_tpu.records import EndOfStream, FrameRecord
from psana_ray_tpu.transport import RingBuffer


def _rec(i, shape=(2, 8, 16), rank=0):
    return FrameRecord(rank, i, np.full(shape, float(i), np.float32), 9.0 + i)


class TestBatcher:
    def test_emits_full_batches(self):
        b = FrameBatcher(batch_size=4)
        outs = [b.push(_rec(i)) for i in range(9)]
        batches = [o for o in outs if o is not None]
        assert len(batches) == 2
        assert batches[0].frames.shape == (4, 2, 8, 16)
        assert batches[0].valid.tolist() == [1, 1, 1, 1]
        assert batches[1].event_idx.tolist() == [4, 5, 6, 7]
        assert b.pending == 1

    def test_flush_pads_tail(self):
        b = FrameBatcher(batch_size=4)
        for i in range(2):
            b.push(_rec(i))
        tail = b.flush()
        assert tail.frames.shape == (4, 2, 8, 16)
        assert tail.valid.tolist() == [1, 1, 0, 0]
        assert tail.num_valid == 2
        np.testing.assert_array_equal(tail.frames[2:], 0)  # padding rows zero
        assert b.flush() is None

    def test_metadata_alignment(self):
        b = FrameBatcher(batch_size=3)
        b.push(_rec(10, rank=5))
        b.push(_rec(11, rank=6))
        out = b.push(_rec(12, rank=7))
        assert out.shard_rank.tolist() == [5, 6, 7]
        assert out.photon_energy.tolist() == pytest.approx([19.0, 20.0, 21.0])

    def test_shape_lock(self):
        b = FrameBatcher(batch_size=2)
        b.push(_rec(0))
        with pytest.raises(ValueError, match="locked shape"):
            b.push(_rec(1, shape=(2, 8, 17)))

    def test_rejects_bad_batch_size(self):
        with pytest.raises(ValueError):
            FrameBatcher(batch_size=0)


class TestBatchesFromQueue:
    def test_drains_until_eos(self):
        q = RingBuffer(maxsize=64)
        for i in range(10):
            q.put(_rec(i))
        q.put(EndOfStream(total_events=10))
        batches = list(batches_from_queue(q, batch_size=4, poll_interval_s=0.001))
        assert [b.num_valid for b in batches] == [4, 4, 2]
        all_idx = np.concatenate([b.event_idx[b.valid.astype(bool)] for b in batches])
        assert all_idx.tolist() == list(range(10))

    def test_max_wait_stops_starved_stream(self):
        q = RingBuffer(maxsize=4)
        q.put(_rec(0))
        batches = list(
            batches_from_queue(q, batch_size=4, poll_interval_s=0.005, max_wait_s=0.02)
        )
        # tail flushed on starvation timeout even without EOS
        assert len(batches) == 1 and batches[0].num_valid == 1

    def test_concurrent_producer(self):
        q = RingBuffer(maxsize=8)

        def produce():
            for i in range(20):
                while not q.put(_rec(i)):
                    pass
            q.put(EndOfStream())

        t = threading.Thread(target=produce)
        t.start()
        batches = list(batches_from_queue(q, batch_size=8, poll_interval_s=0.001))
        t.join()
        assert sum(b.num_valid for b in batches) == 20


class TestDevicePrefetch:
    def test_batches_land_on_device(self):
        q = RingBuffer(maxsize=32)
        for i in range(8):
            q.put(_rec(i))
        q.put(EndOfStream())
        pf = DevicePrefetcher(batches_from_queue(q, 4, poll_interval_s=0.001))
        out = list(pf)
        assert len(out) == 2
        assert isinstance(out[0].frames, jax.Array)
        np.testing.assert_array_equal(np.asarray(out[0].valid), 1)

    def test_error_propagates(self):
        def gen():
            raise RuntimeError("source died")
            yield  # noqa

        pf = DevicePrefetcher(gen())
        with pytest.raises(RuntimeError, match="source died"):
            list(pf)

    def test_exhausted_iterator_keeps_raising(self):
        q = RingBuffer(maxsize=8)
        q.put(_rec(0))
        q.put(EndOfStream())
        pf = DevicePrefetcher(batches_from_queue(q, 1, poll_interval_s=0.001))
        assert len(list(pf)) == 1
        with pytest.raises(StopIteration):  # not a deadlock
            next(pf)

    def test_close_releases_thread_on_early_exit(self):
        q = RingBuffer(maxsize=64)
        for i in range(32):
            q.put(_rec(i))
        q.put(EndOfStream())
        pf = DevicePrefetcher(batches_from_queue(q, 4, poll_interval_s=0.001), prefetch_depth=2)
        next(pf)  # consume one, then abandon
        pf.close()
        assert not pf._thread.is_alive()
        with pytest.raises(StopIteration):
            next(pf)

    def test_num_valid_is_host_int_after_transfer(self):
        q = RingBuffer(maxsize=8)
        for i in range(3):
            q.put(_rec(i))
        q.put(EndOfStream())
        pf = DevicePrefetcher(batches_from_queue(q, 4, poll_interval_s=0.001))
        (batch,) = list(pf)
        assert isinstance(batch.num_valid, int) and batch.num_valid == 3

    def test_sharded_prefetch_on_mesh(self):
        mesh = create_mesh(("data", "model"), (8, 1))
        sharding = batch_sharding(mesh)
        q = RingBuffer(maxsize=32)
        for i in range(8):
            q.put(_rec(i))
        q.put(EndOfStream())
        pf = DevicePrefetcher(batches_from_queue(q, 8, poll_interval_s=0.001), sharding=sharding)
        (batch,) = list(pf)
        # rows split over the 8 data-axis devices
        assert len(batch.frames.sharding.device_set) == 8
        assert batch.frames.shape == (8, 2, 8, 16)


class TestPipelineEndToEnd:
    def test_jitted_consumer_over_mesh(self):
        mesh = create_mesh(("data", "model"), (4, 2))
        sharding = batch_sharding(mesh)
        q = RingBuffer(maxsize=64)
        for i in range(19):  # deliberately not a multiple of 8 -> padded tail
            q.put(_rec(i))
        q.put(EndOfStream(total_events=19))

        pipe = InfeedPipeline(q, batch_size=8, sharding=sharding, poll_interval_s=0.001)

        @jax.jit
        def step(frames, valid):
            # masked per-frame mean: padding rows contribute 0
            per = jnp.mean(frames, axis=(1, 2, 3)) * valid
            return jnp.sum(per)

        totals = []
        seen = pipe.run(lambda b: totals.append(step(b.frames, b.valid)))
        assert seen == 19
        # frames are constant = idx, so sum of per-frame means = sum(range(19))
        assert float(jnp.sum(jnp.stack(totals))) == pytest.approx(sum(range(19)))


class TestMultihostHelpers:
    def test_make_global_batch_single_process(self):
        mesh = create_mesh(("data", "model"), (8, 1))
        local = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
        arr = make_global_batch(local, mesh)
        assert arr.shape == (8, 4)
        assert len(arr.sharding.device_set) == 8
        np.testing.assert_array_equal(np.asarray(arr), local)


class TestPipelineMetrics:
    def test_run_records_latency_and_counts(self):
        """VERDICT r1 weak #8: observe_batch was never called — the p50
        half of the north-star target was unmeasured."""
        q = RingBuffer(maxsize=64)
        for i in range(16):
            q.put(_rec(i))
        q.put(EndOfStream())
        pipe = InfeedPipeline(q, batch_size=8, poll_interval_s=0.001)
        seen = pipe.run(lambda b: jnp.sum(b.frames), block_until_ready=True)
        assert seen == 16
        assert pipe.metrics.batches.count == 2
        assert pipe.metrics.frames.count == 16
        assert pipe.metrics.step_latency.count == 2
        p50 = pipe.metrics.step_latency.quantile(0.5)
        assert np.isfinite(p50) and p50 > 0
        assert "p50" in pipe.metrics.status_line()


class TestTrailingEosInSameBatch:
    def test_sibling_eos_after_completing_marker_survives(self):
        """Two EOS copies popped in ONE get_batch: the copy after the
        tally-completing marker must go back for the sibling consumer
        (code-review r2 finding)."""
        q = RingBuffer(maxsize=16)
        for i in range(3):
            q.put(_rec(i))
        q.put(EndOfStream())  # completes the (single-producer) tally
        q.put(EndOfStream())  # sibling consumer's copy — same get_batch
        batches = list(batches_from_queue(q, 8, poll_interval_s=0.001))
        assert sum(b.num_valid for b in batches) == 3
        leftover = q.get()
        assert isinstance(leftover, EndOfStream)  # survived for the sibling


class TestUint16Stream:
    """Detector-native uint16 ADUs end to end: half the transport and
    host->device bytes of f32; calibration upcasts on device."""

    def test_u16_stream_through_pipeline_and_calib(self):
        import threading

        import jax
        import numpy as np

        from psana_ray_tpu.config import RetrievalMode
        from psana_ray_tpu.infeed import InfeedPipeline
        from psana_ray_tpu.ops import fused_calibrate
        from psana_ray_tpu.records import EndOfStream, FrameRecord
        from psana_ray_tpu.sources import SyntheticSource
        from psana_ray_tpu.transport import RingBuffer

        n = 10
        src = SyntheticSource(
            num_events=n, detector_name="epix100", seed=0, dtype=np.uint16
        )
        ped = np.asarray(src.pedestal())
        gain = np.asarray(src.gain_map())
        mask = np.asarray(src.create_bad_pixel_mask())
        q = RingBuffer(maxsize=16)

        def produce():
            for i in range(n):
                data, e = src.event(i, RetrievalMode.RAW)
                assert data.dtype == np.uint16
                assert q.put_wait(FrameRecord(0, i, data, e), timeout=10)
            assert q.put_wait(EndOfStream(total_events=n), timeout=10)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        pipe = InfeedPipeline(q, batch_size=4, poll_interval_s=0.001)
        outs = []
        seen = pipe.run(
            lambda b: fused_calibrate(b.frames, ped, gain, mask, threshold=10.0),
            on_result=lambda out, b: outs.append((out, b)),
            block_until_ready=True,
        )
        t.join(timeout=10)
        assert seen == n
        for out, b in outs:
            assert b.frames.dtype == np.uint16  # stream stays u16 to the device
            assert out.dtype == np.float32  # calibration upcasts on device
            assert bool(jax.numpy.isfinite(out).all())


class TestPooledBatcher:
    """FrameBatcher(n_buffers=K): recycled batch-buffer arena (round-3
    fan-in profiling: fresh 100+ MB allocations were re-page-faulted every
    batch — see utils/hostmem.py and PERF_NOTES)."""

    def test_pool_reuses_buffers_round_robin(self):
        b = FrameBatcher(batch_size=2, n_buffers=2)
        batches = []
        for i in range(8):
            out = b.push(_rec(i))
            if out is not None:
                batches.append(out)
        assert len(batches) == 4
        # buffer identity cycles with period n_buffers
        ids = [id(x.frames) for x in batches]
        assert ids[0] == ids[2] and ids[1] == ids[3] and ids[0] != ids[1]
        # the most recent n_buffers batches hold correct (un-clobbered) data
        np.testing.assert_array_equal(batches[2].frames[0, 0, 0, 0], 4.0)
        np.testing.assert_array_equal(batches[3].frames[1, 0, 0, 0], 7.0)

    def test_pooled_tail_padding_zeroes_stale_rows(self):
        b = FrameBatcher(batch_size=4, n_buffers=1)
        for i in range(4):
            assert b.push(_rec(i)) is not None or i < 3  # first batch full
        # second, partial fill of the SAME recycled buffer
        b.push(_rec(10))
        tail = b.flush()
        assert tail.num_valid == 1
        np.testing.assert_array_equal(tail.valid, [1, 0, 0, 0])
        # stale rows from the previous batch must be zeroed, not leaked
        np.testing.assert_array_equal(tail.frames[1:], 0.0)
        assert float(tail.frames[0, 0, 0, 0]) == 10.0
        np.testing.assert_array_equal(tail.event_idx[1:], 0)

    def test_eager_copy_releases_source(self):
        # push copies immediately: mutating the source after push must not
        # change the emitted batch
        b = FrameBatcher(batch_size=2)
        r = _rec(1)
        b.push(r)
        r.panels[:] = -1.0
        out = b.push(_rec(2))
        assert float(out.frames[0, 0, 0, 0]) == 1.0


class TestHostOnlyPipeline:
    def test_place_on_device_false_yields_numpy(self):
        q = RingBuffer(maxsize=8)
        for i in range(4):
            q.put(_rec(i))
        q.put(EndOfStream(total_events=4))
        pipe = InfeedPipeline(q, batch_size=4, place_on_device=False)
        got = list(pipe)
        assert len(got) == 1
        assert isinstance(got[0].frames, np.ndarray)  # no device_put copy
        assert got[0].num_valid == 4

    def test_pipeline_rejects_undersized_pool(self):
        q = RingBuffer(maxsize=4)
        with pytest.raises(ValueError, match="batcher_buffers"):
            InfeedPipeline(q, batch_size=2, prefetch_depth=2, batcher_buffers=2)

    def test_fanin_rejects_undersized_pool(self):
        from psana_ray_tpu.infeed import DetectorStream, FanInPipeline

        q = RingBuffer(maxsize=4)
        with pytest.raises(ValueError, match="batcher_buffers"):
            FanInPipeline(
                [DetectorStream("d", q, batch_size=2, batcher_buffers=3)]
            )


def test_stop_stream_ends_run_early_and_closes():
    """A step callback raising StopStream ends run() cleanly: no further
    batches are processed, the pipeline closes, the count so far returns."""
    from psana_ray_tpu.infeed import InfeedPipeline, StopStream
    from psana_ray_tpu.records import EndOfStream, FrameRecord
    from psana_ray_tpu.transport import RingBuffer

    q = RingBuffer(maxsize=64)
    for i in range(32):
        q.put(FrameRecord(0, i, np.zeros((1, 4, 4), np.float32), 1.0))
    q.put(EndOfStream(total_events=32))

    seen = []

    def step(batch):
        seen.append(batch.num_valid)
        if len(seen) == 2:
            raise StopStream

    pipe = InfeedPipeline(q, batch_size=4, place_on_device=False)
    n = pipe.run(step)
    assert len(seen) == 2  # stopped right at the quota
    assert n == 4  # frames counted before the stopping batch
