"""Transport semantics: reference-parity put/get/size, blocking variants,
close/fault propagation, registry rendezvous, backoff envelope."""

import threading
import time

import pytest

from psana_ray_tpu.transport import (
    EMPTY,
    BackoffPolicy,
    Registry,
    RendezvousTimeout,
    RingBuffer,
    TransportClosed,
)


class TestRingParity:
    # semantics of reference shared_queue.py:9-31

    def test_put_get_fifo(self):
        q = RingBuffer(maxsize=4)
        assert q.put("a") and q.put("b")
        assert q.get() == "a"
        assert q.get() == "b"

    def test_put_full_returns_false_never_drops(self):
        q = RingBuffer(maxsize=2)
        assert q.put(1) and q.put(2)
        assert q.put(3) is False  # parity: shared_queue.py:11-14
        assert q.size() == 2
        assert q.get() == 1  # item 3 was NOT enqueued, 1/2 preserved

    def test_get_empty_returns_typed_sentinel(self):
        q = RingBuffer(maxsize=2)
        assert q.get() is EMPTY  # not None — fixes quirk 1 (SURVEY.md §3)
        q.put(None)  # None is valid *data* here, unlike the reference
        assert q.get() is None
        assert q.get() is EMPTY

    def test_size(self):
        q = RingBuffer(maxsize=8)
        for i in range(5):
            q.put(i)
        assert q.size() == 5


class TestRingBlocking:
    def test_get_wait_timeout(self):
        q = RingBuffer(maxsize=2)
        t0 = time.monotonic()
        assert q.get_wait(timeout=0.05) is EMPTY
        assert time.monotonic() - t0 >= 0.04

    def test_put_wait_unblocks_on_get(self):
        q = RingBuffer(maxsize=1)
        q.put("x")
        done = []

        def producer():
            done.append(q.put_wait("y", timeout=2.0))

        t = threading.Thread(target=producer)
        t.start()
        time.sleep(0.05)
        assert q.get() == "x"
        t.join(timeout=2.0)
        assert done == [True]
        assert q.get() == "y"

    def test_get_batch_drains(self):
        q = RingBuffer(maxsize=16)
        for i in range(10):
            q.put(i)
        batch = q.get_batch(max_items=8, timeout=0.1)
        assert batch == list(range(8))
        assert q.size() == 2

    def test_get_batch_timeout_empty(self):
        q = RingBuffer(maxsize=4)
        assert q.get_batch(4, timeout=0.02) == []


class TestFaultDetection:
    # parity role: RayActorError at producer.py:112-114 / data_reader.py:36-37

    def test_ops_raise_after_close(self):
        q = RingBuffer(maxsize=2)
        q.put(1)
        q.close()
        for op in (lambda: q.put(2), q.get, lambda: q.get_wait(0.01)):
            with pytest.raises(TransportClosed):
                op()

    def test_close_wakes_blocked_getter(self):
        q = RingBuffer(maxsize=2)
        err = []

        def getter():
            try:
                q.get_wait(timeout=5.0)
            except TransportClosed as e:
                err.append(e)

        t = threading.Thread(target=getter)
        t.start()
        time.sleep(0.05)
        q.close()
        t.join(timeout=2.0)
        assert len(err) == 1


class TestRegistry:
    # parity: producer.py:35-71 rendezvous protocol

    def test_get_or_create_idempotent(self):
        reg = Registry()
        a = reg.get_or_create("ns", "q", lambda: RingBuffer(4))
        b = reg.get_or_create("ns", "q", lambda: RingBuffer(8))
        assert a is b  # second factory ignored — create-vs-get race closed

    def test_resolve_waits_for_creation(self):
        reg = Registry()
        out = []

        def resolver():
            out.append(reg.resolve("ns", "q", retries=10, interval_s=0.1))

        t = threading.Thread(target=resolver)
        t.start()
        time.sleep(0.05)
        q = reg.get_or_create("ns", "q", lambda: RingBuffer(4))
        t.join(timeout=2.0)
        assert out == [q]

    def test_resolve_timeout(self):
        reg = Registry()
        t0 = time.monotonic()
        with pytest.raises(RendezvousTimeout):
            reg.resolve("ns", "missing", retries=3, interval_s=0.02)
        assert time.monotonic() - t0 >= 0.05

    def test_namespacing(self):
        reg = Registry()
        a = reg.get_or_create("ns1", "q", lambda: RingBuffer(4))
        b = reg.get_or_create("ns2", "q", lambda: RingBuffer(4))
        assert a is not b

    def test_destroy_closes(self):
        reg = Registry()
        q = reg.get_or_create("ns", "q", lambda: RingBuffer(4))
        reg.destroy("ns", "q")
        assert q.closed
        with pytest.raises(RendezvousTimeout):
            reg.resolve("ns", "q", retries=1, interval_s=0.01)


class TestBackoff:
    # parity envelope: producer.py:85-86,108-111

    def test_delay_growth_and_cap(self):
        sleeps = []
        p = BackoffPolicy(base_s=0.1, cap_s=2.0, jitter_s=0.0, sleep=sleeps.append)
        for _ in range(8):
            p.wait()
        assert sleeps[0] == pytest.approx(0.1)
        assert sleeps[1] == pytest.approx(0.2)
        assert sleeps[2] == pytest.approx(0.4)
        assert max(sleeps) <= 2.0
        assert sleeps[-1] == pytest.approx(2.0)

    def test_jitter_bounds(self):
        p = BackoffPolicy(base_s=0.1, cap_s=2.0, jitter_s=0.5, sleep=lambda s: None)
        for _ in range(100):
            d = p.delay()
            assert 0.1 <= d <= 2.5

    def test_reset(self):
        p = BackoffPolicy(sleep=lambda s: None)
        p.wait()
        p.wait()
        assert p.retries == 2
        p.reset()
        assert p.retries == 0


def test_put_front_returns_item_to_head():
    from psana_ray_tpu.transport import RingBuffer

    q = RingBuffer(maxsize=2)
    q.put(1)
    q.put(2)  # full
    assert q.put_front(0)  # recovery path may exceed maxsize
    assert [q.get() for _ in range(3)] == [0, 1, 2]


def test_ring_drain_refuses_puts_serves_gets():
    from psana_ray_tpu.transport import RingBuffer, TransportClosed

    q = RingBuffer(maxsize=4)
    assert q.put(1) and q.put(2)
    q.begin_drain()
    import pytest as _pytest

    with _pytest.raises(TransportClosed):
        q.put(3)
    with _pytest.raises(TransportClosed):
        q.put_wait(3, timeout=0.5)
    assert q.get() == 1 and q.get() == 2
