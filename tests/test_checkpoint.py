"""Checkpoint/resume: stream cursors + orbax train-state roundtrip."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from psana_ray_tpu.checkpoint import (
    StreamCursor,
    restore_train_state,
    save_train_state,
)
from psana_ray_tpu.models import ResNet18
from psana_ray_tpu.parallel import create_mesh
from psana_ray_tpu.parallel.steps import create_train_state
from psana_ray_tpu.sources import SyntheticSource


class TestStreamCursor:
    def test_advance_and_resume(self):
        c = StreamCursor()
        c.advance(0, 5)
        c.advance(0, 3)  # out-of-order completion — high-water mark holds
        c.advance(1, 7)
        assert c.resume_point(0) == 6
        assert c.resume_point(1) == 8
        assert c.resume_point(2) == 0  # untouched shard starts at 0

    def test_save_load_roundtrip(self, tmp_path):
        c = StreamCursor()
        c.advance(3, 41)
        path = str(tmp_path / "run.cursor")
        c.save(path)
        c2 = StreamCursor.load(path)
        assert c2.resume_point(3) == 42

    def test_load_missing_is_fresh(self, tmp_path):
        c = StreamCursor.load(str(tmp_path / "absent.cursor"))
        assert c.resume_point(0) == 0

    def test_source_resumes_past_cursor(self, tmp_path):
        # the end-to-end resume story: crash after event 5, restart skips 0-5
        c = StreamCursor()
        for i in range(6):
            c.advance(0, i)
        src = SyntheticSource(
            num_events=10, detector_name="epix100", start_event=c.resume_point(0)
        )
        assert list(src.shard_event_indices()) == [6, 7, 8, 9]


class TestTrainStateCheckpoint:
    def test_orbax_roundtrip_preserves_params(self, tmp_path):
        mesh = create_mesh(("data", "model"), (4, 2))
        model = ResNet18(num_classes=2, width=16)
        opt = optax.adam(1e-3)
        sample = jnp.ones((8, 32, 32, 1))
        state = create_train_state(model, opt, jax.random.key(0), sample, mesh)

        path = str(tmp_path / "ckpt")
        save_train_state(path, state)

        # fresh state with different rng as the restore template
        template = create_train_state(model, opt, jax.random.key(1), sample, mesh)
        restored = restore_train_state(path, template)

        orig = jax.tree.leaves(state.variables)
        back = jax.tree.leaves(restored.variables)
        for a, b in zip(orig, back):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # restored arrays keep their mesh shardings
        k = restored.variables["params"]["stem"]["kernel"]
        assert k.sharding.spec[-1] == "model"
        assert int(restored.step) == int(state.step)
