"""Checkpoint/resume: stream cursors + orbax train-state roundtrip."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from psana_ray_tpu.checkpoint import (
    StreamCursor,
    restore_train_state,
    save_train_state,
)
from psana_ray_tpu.models import ResNet18
from psana_ray_tpu.parallel import create_mesh
from psana_ray_tpu.parallel.steps import create_train_state
from psana_ray_tpu.sources import SyntheticSource


class TestStreamCursor:
    def test_in_order_advances_watermark(self):
        c = StreamCursor()
        for i in range(6):
            c.advance(0, i)
        assert c.resume_point(0) == 6
        assert c.resume_point(2) == 0  # untouched shard starts at 0

    def test_out_of_order_never_skips_gaps(self):
        """VERDICT r1 weak #6: a max-based mark would resume at 6 here and
        silently skip events 0-2 and 4, which were never processed."""
        c = StreamCursor()
        c.advance(0, 5)
        c.advance(0, 3)
        assert c.resume_point(0) == 0  # nothing contiguous done yet
        assert c.pending_count(0) == 2
        for i in (0, 1, 2):
            c.advance(0, i)
        assert c.resume_point(0) == 4  # 0-3 contiguous; 5 still pending
        c.advance(0, 4)
        assert c.resume_point(0) == 6  # gap closed, pending folded in
        assert c.pending_count(0) == 0

    def test_strided_shards(self):
        # shard r of N owns r, r+N, ... (sources.base.shard_indices)
        c = StreamCursor(stride=4)
        c.advance(1, 1)
        c.advance(1, 9)  # out of order: 5 missing
        assert c.resume_point(1) == 5
        c.advance(1, 5)
        assert c.resume_point(1) == 13
        assert c.resume_point(3) == 3  # untouched shard starts at its base

    def test_save_load_roundtrip(self, tmp_path):
        c = StreamCursor()
        for i in range(42):
            c.advance(0, i)
        c.advance(0, 50)  # pending — must NOT survive the roundtrip
        path = str(tmp_path / "run.cursor")
        c.save(path)
        c2 = StreamCursor.load(path)
        assert c2.resume_point(0) == 42  # at-least-once: 50 will re-run

    def test_load_legacy_format(self, tmp_path):
        import json

        path = str(tmp_path / "old.cursor")
        with open(path, "w") as f:
            json.dump({"0": 9}, f)  # pre-watermark {rank: idx} format
        c = StreamCursor.load(path)
        assert c.resume_point(0) == 10

    def test_load_missing_is_fresh(self, tmp_path):
        c = StreamCursor.load(str(tmp_path / "absent.cursor"))
        assert c.resume_point(0) == 0

    def test_source_resumes_past_cursor(self, tmp_path):
        # the end-to-end resume story: crash after event 5, restart skips 0-5
        c = StreamCursor()
        for i in range(6):
            c.advance(0, i)
        src = SyntheticSource(
            num_events=10, detector_name="epix100", start_event=c.resume_point(0)
        )
        assert list(src.shard_event_indices()) == [6, 7, 8, 9]


class TestTrainStateCheckpoint:
    def test_orbax_roundtrip_preserves_params(self, tmp_path):
        mesh = create_mesh(("data", "model"), (4, 2))
        model = ResNet18(num_classes=2, width=16)
        opt = optax.adam(1e-3)
        sample = jnp.ones((8, 32, 32, 1))
        state = create_train_state(model, opt, jax.random.key(0), sample, mesh)

        path = str(tmp_path / "ckpt")
        save_train_state(path, state)

        # fresh state with different rng as the restore template
        template = create_train_state(model, opt, jax.random.key(1), sample, mesh)
        restored = restore_train_state(path, template)

        orig = jax.tree.leaves(state.variables)
        back = jax.tree.leaves(restored.variables)
        for a, b in zip(orig, back):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # restored arrays keep their mesh shardings
        k = restored.variables["params"]["stem"]["kernel"]
        assert k.sharding.spec[-1] == "model"
        assert int(restored.step) == int(state.step)


class TestCursorDuplicateAndMisconfigGuards:
    """Round-4 review findings: at-least-once duplicates below the
    watermark must not leak into the pending set, and stride/shard
    misconfigurations must fail at advance time, not stick silently."""

    def test_duplicate_below_watermark_does_not_leak_pending(self):
        from psana_ray_tpu.checkpoint import StreamCursor

        c = StreamCursor(stride=1)
        for i in range(5):
            c.advance(0, i)
        assert c.positions[0] == 4 and c.pending_count(0) == 0
        for i in range(5):  # TCP-retry style redelivery of done events
            c.advance(0, i)
        assert c.positions[0] == 4
        assert c.pending_count(0) == 0  # no unbounded growth

    def test_rank_outside_stride_raises(self):
        from psana_ray_tpu.checkpoint import StreamCursor

        c = StreamCursor(stride=2)
        with pytest.raises(ValueError, match="outside"):
            c.advance(3, 3)

    def test_misaligned_idx_raises(self):
        from psana_ray_tpu.checkpoint import StreamCursor

        c = StreamCursor(stride=4)
        with pytest.raises(ValueError, match="strided sequence"):
            c.advance(1, 2)  # shard 1 of 4 owns 1, 5, 9, ...
