"""Negotiated wire compression (ISSUE 9): codec round trips over every
wire dtype, expansion fallback, hostile-payload fail-fast with the
in-flight requeue contract intact, mixed-codec connections on one
server, old-peer degradation, lazy relay pass-through, and
zero-leaked-leases after decode errors.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from faultproxy import ThrottleProxy
from psana_ray_tpu.records import (
    EndOfStream,
    FrameRecord,
    LazyFrameRecord,
    narrow_panels,
)
from psana_ray_tpu.transport import codec as codec_mod
from psana_ray_tpu.transport.codec import (
    CODEC_NONE,
    TAG_COMPRESSED,
    WIRE_COMPRESS_MIN,
    available_codecs,
    compress_encoded_parts,
    decode_payload,
    encode_payload,
    encode_payload_parts,
    get_codec,
    negotiate_codec,
    payload_nbytes,
)
from psana_ray_tpu.transport.registry import TransportClosed
from psana_ray_tpu.transport.ring import RingBuffer
from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer
from psana_ray_tpu.utils.bufpool import BufferPool

RNG = np.random.default_rng(42)
SHUFFLE = get_codec("shuffle-rle")


def detector_u16(shape=(4, 128, 128)):
    """Pedestal + noise + sparse peaks — compressible detector content."""
    ped = 2000 + 200 * np.sin(np.linspace(0, 9, int(np.prod(shape)))).reshape(shape)
    f = (ped + RNG.normal(0, 3, shape)).clip(0, 65535).astype(np.uint16)
    hits = RNG.random(shape) < 1e-3
    f[hits] += RNG.integers(500, 3000, int(hits.sum())).astype(np.uint16)
    return f


def wire_roundtrip(rec, codec=SHUFFLE, pool=None):
    """Compress -> join to wire bytes -> decode; returns the decoded
    record (leases released)."""
    pool = pool or BufferPool()
    parts = encode_payload_parts(rec)
    wparts, lease = compress_encoded_parts(rec, parts, codec, pool)
    wire = b"".join(bytes(p) for p in wparts)
    if lease is not None:
        lease.release()
    return decode_payload(wire), wire, b"".join(bytes(p) for p in parts)


class TestCodecRoundTrip:
    @pytest.mark.parametrize(
        "dtype", [np.float32, np.float64, np.uint16, np.int32, np.uint8, np.int16]
    )
    def test_every_wire_dtype_roundtrips(self, dtype):
        # content with structure so most dtypes actually compress; the
        # round trip must hold either way (compressed or fallback)
        base = np.cumsum(RNG.normal(0, 2, (2, 48, 48))).reshape(2, 48, 48)
        panels = base.astype(dtype)
        rec = FrameRecord(3, 17, panels, 8.2, timestamp=1.5)
        out, wire, raw = wire_roundtrip(rec)
        assert out.equals(rec)
        assert out.panels.dtype == np.dtype(dtype)

    def test_noncontiguous_strided_panels(self):
        full = detector_u16((4, 128, 256))
        rec = FrameRecord(0, 5, full[:, ::2, ::4], 1.0)
        assert not rec.panels.flags.c_contiguous
        out, wire, raw = wire_roundtrip(rec)
        assert out.equals(rec)
        assert len(wire) < len(raw)  # strided content still compresses

    def test_detector_frames_compress_well(self):
        rec = FrameRecord(0, 1, detector_u16(), 9.5)
        out, wire, raw = wire_roundtrip(rec)
        assert out.equals(rec)
        assert len(raw) / len(wire) >= 2.0, "detector-like u16 must beat 2x"

    def test_pooled_decode_is_zero_copy_with_lease(self):
        pool = BufferPool()
        rec = FrameRecord(0, 1, detector_u16(), 9.5)
        _, wire, _ = wire_roundtrip(rec)
        lease = pool.lease(len(wire))
        lease.mv[:] = wire
        out = decode_payload(lease.mv, lease=lease)
        assert out.equals(rec)
        # the decompressed buffer lease rides the record; the compressed
        # staging lease goes straight back — a plain consumer never
        # relays, so caching the wire bytes would only double pool
        # residency per in-flight frame (the relay's lazy=True receive
        # is the path that keeps them)
        assert out.lease is not None and out.wire_cache is None
        assert pool.stats()["leases"] == 1
        out.release()
        assert pool.stats()["leases"] == 0

    def test_small_payloads_never_compress(self):
        rec = FrameRecord(0, 1, np.zeros((1, 4, 4), np.uint16), 1.0)
        assert rec.nbytes < WIRE_COMPRESS_MIN
        parts = encode_payload_parts(rec)
        wparts, lease = compress_encoded_parts(rec, parts, SHUFFLE, BufferPool())
        assert lease is None and wparts is parts

    def test_eos_and_pickle_never_compress(self):
        pool = BufferPool()
        for item in (EndOfStream(total_events=4), {"k": 1}):
            parts = encode_payload_parts(item)
            wparts, lease = compress_encoded_parts(item, parts, SHUFFLE, pool)
            assert lease is None and wparts is parts


class TestExpansionFallback:
    def test_uniform_noise_falls_back_to_raw(self):
        pool = BufferPool()
        rec = FrameRecord(0, 1, RNG.integers(0, 65536, (4, 64, 64), np.uint16), 1.0)
        parts = encode_payload_parts(rec)
        wparts, lease = compress_encoded_parts(rec, parts, SHUFFLE, pool)
        assert lease is None and wparts is parts  # identical raw framing
        assert b"".join(bytes(p) for p in wparts) == encode_payload(rec)
        assert pool.stats()["leases"] == 0  # staging lease went back

    def test_oversized_raw_frame_fails_fast_at_sender(self, monkeypatch):
        # the raw path's 256 MB send cap must survive compression: a
        # frame whose COMPRESSED size passes the transport wire check
        # but whose raw_len trips the receiver's guard would kill the
        # connection and ride the windowed resend forever (poison
        # record) — so the cap applies to the RAW size, before encode
        from psana_ray_tpu.transport import codec as codec_mod

        monkeypatch.setattr(codec_mod, "_MAX_RAW_PAYLOAD", 4096)
        pool = BufferPool()
        rec = FrameRecord(0, 1, detector_u16(), 9.5)
        parts = encode_payload_parts(rec)
        with pytest.raises(ValueError, match="exceeds wire maximum"):
            compress_encoded_parts(rec, parts, SHUFFLE, pool)
        assert pool.stats()["leases"] == 0

    def test_fallback_frames_relay_correctly(self):
        srv = TcpQueueServer(RingBuffer(4), host="127.0.0.1").serve_background()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port, codec="shuffle-rle")
            rec = FrameRecord(
                0, 1, RNG.integers(0, 65536, (4, 64, 64), np.uint16), 1.0
            )
            assert c.put(rec)
            out = c.get()
            assert out.equals(rec)
            c.disconnect()
        finally:
            srv.shutdown()


class TestNegotiation:
    def test_server_picks_first_known_codec(self):
        assert negotiate_codec(["nope", "shuffle-rle"]) is SHUFFLE
        assert negotiate_codec(["none", "shuffle-rle"]) is None
        assert negotiate_codec(["bogus", "alsobogus"]) is None

    def test_get_codec_rejects_unknown_names(self):
        with pytest.raises(ValueError, match="unknown wire codec"):
            get_codec("snappy-ultra")
        assert get_codec(CODEC_NONE) is None
        assert get_codec(None) is None
        assert "shuffle-rle" in available_codecs()

    def test_client_negotiates_and_survives_reconnect(self):
        srv = TcpQueueServer(RingBuffer(4), host="127.0.0.1").serve_background()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port, codec="shuffle-rle")
            assert c._codec is not None
            rec = FrameRecord(0, 1, detector_u16(), 9.5)
            assert c.put(rec)
            assert c.get().equals(rec)
            # sever the socket: the reconnect must renegotiate
            c._sock.close()
            assert c.put(rec)
            assert c._codec is not None
            assert c.get().equals(rec)
            c.disconnect()
        finally:
            srv.shutdown()

    def test_put_wait_compresses_once_under_backpressure(self, monkeypatch):
        """A backpressured put_wait retries the bounded-wait round trip
        but must pay the codec ONCE per frame: the compressed bytes
        depend only on (item, codec), so the encode is cached across
        full-queue retries (re-encoded only when a reconnect
        renegotiates the codec)."""
        from psana_ray_tpu.transport import tcp as tcp_mod
        from psana_ray_tpu.transport.codec import CODEC_STATS

        monkeypatch.setattr(tcp_mod, "_SERVER_WAIT_CAP_S", 0.15)
        srv = TcpQueueServer(RingBuffer(1), host="127.0.0.1").serve_background()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port, codec="shuffle-rle")
            blocker = FrameRecord(0, 0, detector_u16(), 9.5)
            assert c.put(blocker)  # queue (size 1) now full
            s0 = CODEC_STATS.stats()["frames_compressed_total"]
            rec = FrameRecord(0, 1, detector_u16(), 9.5)
            # >= 3 bounded-wait round trips before the deadline
            assert not c.put_wait(rec, timeout=0.6)
            assert CODEC_STATS.stats()["frames_compressed_total"] == s0 + 1
            # drain the blocker; the retried put then lands intact
            assert c.get().equals(blocker)
            assert c.put_wait(rec, timeout=5)
            assert c.get().equals(rec)
            c.disconnect()
        finally:
            srv.shutdown()

    def test_old_peer_degrades_to_none(self, monkeypatch):
        """A server that predates the 'Z' opcode answers protocol-error
        and drops the connection; the client must degrade to
        uncompressed (latched — no renegotiation storm) and keep
        working, not crash."""
        from psana_ray_tpu.transport import evloop

        monkeypatch.delitem(evloop._OPS, ord("Z"))
        srv = TcpQueueServer(RingBuffer(4), host="127.0.0.1").serve_background()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port, codec="shuffle-rle")
            assert c._codec is None and c._codec_refused
            rec = FrameRecord(0, 1, detector_u16((2, 32, 32)), 9.5)
            assert c.put(rec)  # reconnects (old server dropped us), raw
            out = c.get()
            assert out.equals(rec)
            assert out.wire_cache is None  # nothing was compressed
            c.disconnect()
        finally:
            srv.shutdown()

    def test_malformed_negotiation_reply_degrades_to_none(self, monkeypatch):
        """A buggy peer/proxy answering 'Z' with a codec name the client
        never advertised must degrade the client to uncompressed (same
        latch as the old-peer refusal), not surface a raw ValueError
        from the middle of connect/reconnect."""
        from psana_ray_tpu.transport import evloop

        class _Spoofed:
            name = "bogus-codec"

            def __getattr__(self, attr):
                return getattr(SHUFFLE, attr)

        monkeypatch.setattr(evloop, "negotiate_codec", lambda names: _Spoofed())
        srv = TcpQueueServer(RingBuffer(4), host="127.0.0.1").serve_background()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port, codec="shuffle-rle")
            assert c._codec is None and c._codec_refused
            rec = FrameRecord(0, 1, detector_u16((2, 32, 32)), 9.5)
            assert c.put(rec)  # raw put on the still-healthy connection
            out = c.get()
            assert out.equals(rec)
            c.disconnect()
        finally:
            srv.shutdown()

    def test_mixed_codec_connections_on_one_server(self):
        pool = BufferPool()
        srv = TcpQueueServer(
            RingBuffer(16), host="127.0.0.1", pool=pool
        ).serve_background()
        try:
            prod = TcpQueueClient("127.0.0.1", srv.port, pool=pool, codec="shuffle-rle")
            cons_c = TcpQueueClient(
                "127.0.0.1", srv.port, pool=pool, codec="shuffle-rle"
            )
            cons_raw = TcpQueueClient("127.0.0.1", srv.port, pool=pool)
            recs = [FrameRecord(0, i, detector_u16() + i, 9.5) for i in range(4)]
            for r in recs:
                assert prod.put(r)
            assert cons_c.get().equals(recs[0])
            assert cons_raw.get().equals(recs[1])
            assert cons_c.get().equals(recs[2])
            assert cons_raw.get().equals(recs[3])
            for c in (prod, cons_c, cons_raw):
                c.disconnect()
        finally:
            srv.shutdown()


class TestHostilePayloads:
    def _wire(self, rec=None):
        rec = rec or FrameRecord(0, 1, detector_u16(), 9.5)
        _, wire, _ = wire_roundtrip(rec)
        return wire

    def test_truncated_payload_is_connection_error(self):
        wire = self._wire()
        for cut in (3, 9, len(wire) // 2, len(wire) - 1):
            with pytest.raises(ConnectionError, match="compressed"):
                decode_payload(wire[:cut])

    def test_bitflips_in_framing_are_connection_errors(self):
        wire = bytearray(self._wire())
        wire[1] = 0xEE  # unknown codec id
        with pytest.raises(ConnectionError, match="unknown wire codec"):
            decode_payload(bytes(wire))
        wire = bytearray(self._wire())
        struct.pack_into("<I", wire, 2, 1 << 30)  # absurd raw_len
        with pytest.raises(ConnectionError, match="compressed"):
            decode_payload(bytes(wire))

    def test_nested_compressed_framing_is_connection_error(self):
        """No encoder nests 'C' in 'C': a payload that decompresses to
        ANOTHER compressed payload is a crafted recursion/amplification
        bomb and must die as a ConnectionError at the first level, not
        recurse through decode_payload."""
        wire = self._wire(FrameRecord(0, 1, detector_u16((1, 64, 64)), 9.5))
        assert wire[0] == TAG_COMPRESSED[0]  # fixture really compressed
        assert len(wire) < 0xFFFF  # head_len is u16 in the prefix
        # outer frame: the inner compressed payload rides as the verbatim
        # head, plus a genuinely-compressed padding body so the outer
        # level exercises a REAL decompress before the nested check
        pad = bytes(4096)
        scratch = bytearray(8192)
        clen = SHUFFLE.compress(memoryview(pad), 1, memoryview(scratch))
        assert clen
        outer = (
            TAG_COMPRESSED
            + struct.pack("<BIH", wire[1], len(wire) + len(pad), len(wire))
            + wire
            + bytes(scratch[:clen])
        )
        with pytest.raises(ConnectionError, match="nested"):
            decode_payload(outer)

    def test_trailing_garbage_is_a_connection_error(self):
        wire = self._wire()
        with pytest.raises(ConnectionError, match="compressed"):
            decode_payload(wire + b"\x00" * 7)

    def test_zero_leaked_leases_after_decode_error(self):
        pool = BufferPool()
        wire = self._wire()
        bad = wire[: len(wire) - 9]
        lease = pool.lease(len(bad))
        lease.mv[:] = bad
        with pytest.raises(ConnectionError):
            decode_payload(lease.mv, lease=lease)
        assert pool.stats()["leases"] == 0, pool.stats()

    def test_hostile_rle_counts_fail_before_allocation(self, monkeypatch):
        """An RLE plane whose counts sum to far more than the plane size
        must raise BEFORE np.repeat materializes the expansion — a
        hostile peer could otherwise claim terabytes inside a payload
        that passes every length cap."""
        n_runs = 1000
        buf = bytearray(struct.pack("<I", n_runs))
        buf += b"\xaa" * n_runs  # run values
        buf += struct.pack("<H", 65535) * n_runs  # counts: sum ~65.5M

        def boom(*a, **k):
            raise AssertionError("np.repeat ran before the size check")

        monkeypatch.setattr(codec_mod.np, "repeat", boom)
        with pytest.raises(ValueError, match="expands to"):
            codec_mod._decode_plane(
                memoryview(bytes(buf)), 0, codec_mod._PLANE_RLE, len(buf), 4096
            )

    def test_validate_mirrors_decompress(self):
        rec = FrameRecord(0, 1, detector_u16(), 9.5)
        pool = BufferPool()
        parts = encode_payload_parts(rec)
        wparts, lease = compress_encoded_parts(rec, parts, SHUFFLE, pool)
        body = bytes(wparts[1])
        SHUFFLE.validate(memoryview(body), rec.nbytes)  # valid: no raise
        for cut in (1, 6, len(body) // 3, len(body) - 1):
            with pytest.raises(ValueError):
                SHUFFLE.validate(memoryview(body[:cut]), rec.nbytes)
        lease.release()

    def test_server_kills_conn_on_corrupt_put_and_requeue_survives(self):
        """A hostile compressed PUT dies as a CONNECTION error at
        receive (the server kills that connection — it never queues a
        poison frame), while the queue keeps serving and the standard
        in-flight requeue contract still runs for deliveries that die
        unacked — corruption never becomes silent loss NOR silent
        acceptance."""
        srv = TcpQueueServer(RingBuffer(8), host="127.0.0.1").serve_background()
        try:
            prod = TcpQueueClient("127.0.0.1", srv.port)
            rec = FrameRecord(0, 7, detector_u16((2, 64, 64)), 9.5)
            assert prod.put(rec)
            # raw protocol driving: a corrupt compressed PUT must kill
            # the connection (EOF, no status answer) — ConnectionError
            # semantics server-side, not a queued poison frame
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
            garbage = TAG_COMPRESSED + struct.pack("<BIH", 1, 4096, 2) + b"xx"
            s.sendall(b"P" + struct.pack("<I", len(garbage)) + garbage)
            s.settimeout(5.0)
            died = False
            try:
                died = s.recv(4096) == b""
            except OSError:
                died = True
            s.close()
            assert died, "server answered a corrupt compressed PUT"
            # the queue still serves; a delivery that dies UNACKED after
            # the corruption event still redelivers (requeue intact)
            s2 = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
            s2.sendall(b"G")
            assert s2.recv(1) == b"1"
            (n,) = struct.unpack("<I", s2.recv(4))
            got = 0
            while got < n:
                got += len(s2.recv(1 << 16))
            s2.close()  # no BYE, no next opcode: delivery stays unacked
            cons = TcpQueueClient("127.0.0.1", srv.port)
            out = cons.get_wait(timeout=10.0)
            assert isinstance(out, FrameRecord) and out.equals(rec)
            assert cons.size() == 0  # exactly one frame, no poison extras
            prod.disconnect()
            cons.disconnect()
        finally:
            srv.shutdown()


class TestLazyRelay:
    def test_lazy_frame_semantics(self):
        pool = BufferPool()
        rec = FrameRecord(2, 9, detector_u16(), 7.5, timestamp=2.5)
        _, wire, _ = wire_roundtrip(rec)
        lease = pool.lease(len(wire))
        lease.mv[:] = wire
        out = decode_payload(lease.mv, lease=lease, lazy=True)
        assert isinstance(out, LazyFrameRecord)
        # header fields real, no decompression yet (only the cache +
        # nothing else checked out beyond the wire lease)
        assert (out.shard_rank, out.event_idx) == (2, 9)
        assert out.nbytes == rec.nbytes
        assert out.lease is None and out.wire_cache is not None
        assert pool.stats()["leases"] == 1
        # first panels touch inflates into a lease
        assert np.array_equal(out.panels, rec.panels)
        assert out.lease is not None
        assert pool.stats()["leases"] == 2
        out.release()
        assert pool.stats()["leases"] == 0

    def test_lazy_materialize_detaches(self):
        pool = BufferPool()
        rec = FrameRecord(0, 1, detector_u16(), 9.5)
        _, wire, _ = wire_roundtrip(rec)
        lease = pool.lease(len(wire))
        lease.mv[:] = wire
        out = decode_payload(lease.mv, lease=lease, lazy=True)
        owned = out.materialize()
        assert type(owned) is FrameRecord
        assert owned.lease is None and owned.wire_cache is None
        assert owned.equals(rec)
        assert pool.stats()["leases"] == 0

    def test_lazy_corrupt_payload_still_fails_at_receive(self):
        pool = BufferPool()
        rec = FrameRecord(0, 1, detector_u16(), 9.5)
        _, wire, _ = wire_roundtrip(rec)
        bad = wire[: len(wire) - 5]
        lease = pool.lease(len(bad))
        lease.mv[:] = bad
        with pytest.raises(ConnectionError):
            decode_payload(lease.mv, lease=lease, lazy=True)
        assert pool.stats()["leases"] == 0

    def test_corrupt_raw_head_is_connection_error_on_eager_path(self):
        # a stream that DECOMPRESSES cleanly but whose raw head is
        # garbage (flipped frame-magic byte rides the prefix raw) is
        # corruption all the same: the eager consumer path must kill
        # the connection like every other corruption — not leak a
        # ValueError out of get() — and hand both leases back without
        # the GC __del__ backstop
        pool = BufferPool()
        rec = FrameRecord(0, 1, detector_u16(), 9.5)
        _, wire, _ = wire_roundtrip(rec)
        bad = bytearray(wire)
        bad[10] ^= 0xFF  # inside the raw head's frame magic
        lease = pool.lease(len(bad))
        lease.mv[:] = bytes(bad)
        with pytest.raises(ConnectionError):
            decode_payload(lease.mv, lease=lease)
        assert pool.stats()["leases"] == 0

    def test_passthrough_resends_identical_bytes_without_inflating(self):
        """The relay's send path (cached_wire_parts, consulted BEFORE
        any raw-part building) must re-send the exact received bytes
        and must NOT touch panels — the zero-codec-CPU relay claim,
        pinned."""
        from psana_ray_tpu.transport.codec import CODEC_STATS, cached_wire_parts

        pool = BufferPool()
        rec = FrameRecord(0, 1, detector_u16(), 9.5)
        _, wire, _ = wire_roundtrip(rec)
        lease = pool.lease(len(wire))
        lease.mv[:] = wire
        out = decode_payload(lease.mv, lease=lease, lazy=True)
        d0 = CODEC_STATS.stats()["frames_decompressed_total"]
        wparts = cached_wire_parts(out, SHUFFLE)
        assert wparts is not None and len(wparts) == 1
        assert bytes(wparts[0]) == wire
        assert "_panels" not in out.__dict__, "pass-through inflated panels"
        assert CODEC_STATS.stats()["frames_decompressed_total"] == d0
        # a DIFFERENT codec id misses the cache (re-encode path)
        class _Other:
            codec_id = 99

        assert cached_wire_parts(out, _Other()) is None
        # the compress_encoded_parts fallback arm still passes through
        parts2 = encode_payload_parts(out)  # this one inflates (mixed path)
        wparts2, staging = compress_encoded_parts(out, parts2, SHUFFLE, pool)
        assert staging is None and bytes(wparts2[0]) == wire
        out.release()

    def test_lazy_frame_relays_to_raw_consumer(self):
        """Mixed path: a compressed PUT relayed to an uncompressed
        consumer forces the server to inflate — bytes must be right."""
        srv = TcpQueueServer(RingBuffer(4), host="127.0.0.1").serve_background()
        try:
            prod = TcpQueueClient("127.0.0.1", srv.port, codec="shuffle-rle")
            cons = TcpQueueClient("127.0.0.1", srv.port)
            rec = FrameRecord(0, 3, detector_u16(), 9.5)
            assert prod.put(rec)
            assert cons.get().equals(rec)
            prod.disconnect()
            cons.disconnect()
        finally:
            srv.shutdown()


class TestWireSavings:
    def test_relay_wire_bytes_shrink_deterministically(self):
        """The deterministic acceptance proxy (no wall clocks — this
        box's CPU share flutters): the SAME stream through the SAME
        byte-counting proxy must put >= 2x fewer bytes on the wire
        compressed than raw; the >= 2x FPS number through the real
        50 MB/s throttle is recorded by bench.py (measured 3.19x)."""
        frames = [FrameRecord(0, i, detector_u16(), 9.5) for i in range(4)]

        def run(codec):
            srv = TcpQueueServer(RingBuffer(8), host="127.0.0.1").serve_background()
            # generous rate: counting bytes, not modelling bandwidth
            proxy = ThrottleProxy("127.0.0.1", srv.port, 1e9)
            try:
                prod = TcpQueueClient("127.0.0.1", proxy.port, codec=codec)
                cons = TcpQueueClient("127.0.0.1", proxy.port, codec=codec)
                for r in frames:
                    assert prod.put(r)
                for r in frames:
                    assert cons.get().equals(r)
                prod.disconnect()
                cons.disconnect()
                return proxy.bytes_forwarded("up") + proxy.bytes_forwarded("down")
            finally:
                proxy.close()
                srv.shutdown()

        raw_bytes = run(None)
        comp_bytes = run("shuffle-rle")
        assert comp_bytes * 2 <= raw_bytes, (comp_bytes, raw_bytes)

    def test_throttle_proxy_actually_throttles(self):
        """The bandwidth proxy must cap throughput near its rate — the
        delay-line proxy models latency and could not run this A/B."""
        srv = TcpQueueServer(RingBuffer(8), host="127.0.0.1").serve_background()
        rate = 2e6
        proxy = ThrottleProxy("127.0.0.1", srv.port, rate, burst_s=0.05)
        try:
            c = TcpQueueClient("127.0.0.1", proxy.port)
            payload = np.zeros((1, 512, 512), np.uint16)  # 512 KB
            t0 = time.monotonic()
            for i in range(8):  # ~4.2 MB up
                assert c.put_wait(FrameRecord(0, i, payload, 1.0), timeout=30)
            dt = time.monotonic() - t0
            sent = proxy.bytes_forwarded("up")
            # must take at least (bytes - burst) / rate
            floor = (sent - rate * 0.05) / rate * 0.7  # 30% slack
            assert dt >= floor, (dt, floor, sent)
            c.disconnect()
        finally:
            proxy.close()
            srv.shutdown()


class TestDtypeNarrowing:
    def test_narrow_panels_rounds_and_clips(self):
        f = np.array([[-5.4, 0.5, 70000.2, 123.6]], np.float32).reshape(1, 1, 4)
        out = narrow_panels(f, "uint16")
        assert out.dtype == np.uint16
        assert out.ravel().tolist() == [0, 0, 65535, 124]

    def test_narrow_panels_nan_maps_to_zero(self):
        # calibrated frames mark bad pixels NaN; NaN→int casts are
        # platform-undefined in numpy, so the narrowing must map them
        # deterministically (0, the masked-pixel convention) and ±inf
        # to the dtype bounds — with no RuntimeWarning on the hot path
        f = np.array([[np.nan, np.inf, -np.inf, 7.2]], np.float32).reshape(1, 1, 4)
        with np.errstate(invalid="raise"):
            out = narrow_panels(f, "uint16")
        assert out.ravel().tolist() == [0, 65535, 0, 7]

    def test_narrow_panels_float_target(self):
        f = np.linspace(0, 1, 8, dtype=np.float64).reshape(1, 2, 4)
        out = narrow_panels(f, "float32")
        assert out.dtype == np.float32

    def test_narrow_panels_noop_and_unknown(self):
        f = np.zeros((1, 2, 2), np.uint16)
        assert narrow_panels(f, "uint16") is f
        with pytest.raises(ValueError, match="not wire-codable"):
            narrow_panels(f, "complex64")

    def test_producer_cli_wires_the_flags(self):
        from psana_ray_tpu.producer import parse_arguments

        cfg, _ = parse_arguments(["--wire_codec", "auto", "--wire_dtype", "uint16"])
        assert cfg.transport.wire_codec == "auto"
        assert cfg.transport.wire_dtype == "uint16"
        with pytest.raises(ValueError, match="unknown wire codec"):
            parse_arguments(["--wire_codec", "zstd-hyper"])


class TestStreamedCompressed:
    def test_streamed_drain_compressed_end_to_end(self):
        pool = BufferPool()
        srv = TcpQueueServer(
            RingBuffer(16), host="127.0.0.1", pool=pool
        ).serve_background()
        try:
            prod = TcpQueueClient("127.0.0.1", srv.port, pool=pool, codec="shuffle-rle")
            cons = TcpQueueClient(
                "127.0.0.1", srv.port, pool=pool, codec="shuffle-rle"
            )
            cons.stream_open(window=8)
            recs = [FrameRecord(0, i, detector_u16() + i, 9.5) for i in range(6)]

            def produce():
                for r in recs:
                    assert prod.put_pipelined(r, deadline=time.monotonic() + 30)
                assert prod.flush_puts(deadline=time.monotonic() + 30)

            t = threading.Thread(target=produce, daemon=True)
            t.start()
            got = []
            deadline = time.monotonic() + 30
            while len(got) < len(recs) and time.monotonic() < deadline:
                got += cons.get_batch_stream(8, timeout=1.0)
            t.join(timeout=10)
            assert len(got) == len(recs)
            for r, o in zip(recs, got):
                assert o.equals(r)
            prod.disconnect()
            cons.disconnect()
        finally:
            srv.shutdown()
        s = codec_mod.CODEC_STATS.stats()
        assert s["frames_compressed_total"] > 0

    def test_compressed_conn_death_redelivers(self):
        """At-least-once through the codec: kill a compressed streamed
        consumer mid-window; the unacked tail redelivers to a sibling
        byte-correct."""
        srv = TcpQueueServer(RingBuffer(16), host="127.0.0.1").serve_background()
        try:
            prod = TcpQueueClient("127.0.0.1", srv.port, codec="shuffle-rle")
            cons = TcpQueueClient("127.0.0.1", srv.port, codec="shuffle-rle")
            reader = cons.stream_open(window=4)
            recs = [FrameRecord(0, i, detector_u16((2, 64, 64)) + i, 9.5) for i in range(4)]
            for r in recs:
                assert prod.put(r)
            first = reader.get_batch_stream(1, timeout=10.0)
            assert first and first[0].equals(recs[0])
            # die without acking: everything pushed-but-unacked requeues
            cons._sock.close()
            sib = TcpQueueClient("127.0.0.1", srv.port, codec="shuffle-rle")
            seen = []
            deadline = time.monotonic() + 20
            while len(seen) < 4 and time.monotonic() < deadline:
                item = sib.get_wait(timeout=1.0)
                if isinstance(item, FrameRecord):
                    seen.append(item.event_idx)
            # all four frames (incl. the unacked first) land somewhere
            assert sorted(set(seen)) == [0, 1, 2, 3], seen
            prod.disconnect()
            sib.disconnect()
        finally:
            srv.shutdown()
