"""Data sources: protocol surface, determinism, sharding, replay."""

import numpy as np
import pytest

from psana_ray_tpu.config import RetrievalMode
from psana_ray_tpu.sources import DETECTORS, ReplaySource, SyntheticSource, open_source
from psana_ray_tpu.sources.base import shard_indices


# small detector for fast tests
SMALL = dict(num_events=8, detector_name="epix100")


def test_detector_geometries():
    assert DETECTORS["epix10k2M"].frame_shape == (16, 352, 384)
    assert DETECTORS["jungfrau4M"].frame_shape == (8, 512, 1024)


def test_protocol_surface():
    src = SyntheticSource(**SMALL)
    mask = src.create_bad_pixel_mask()
    assert mask.shape == DETECTORS["epix100"].frame_shape
    assert mask.dtype == np.uint8
    events = list(src.iter_events(RetrievalMode.CALIB))
    assert len(events) == 8
    data, energy = events[0]
    assert data.shape == DETECTORS["epix100"].frame_shape
    assert isinstance(energy, float)


def test_determinism():
    a = SyntheticSource(seed=7, **SMALL).event(3)
    b = SyntheticSource(seed=7, **SMALL).event(3)
    np.testing.assert_array_equal(a[0], b[0])
    assert a[1] == b[1]
    c = SyntheticSource(seed=8, **SMALL).event(3)
    assert not np.array_equal(a[0], c[0])


def test_shard_indices_disjoint_exhaustive():
    n, shards = 103, 4
    all_idx = np.concatenate([shard_indices(n, r, shards) for r in range(shards)])
    assert sorted(all_idx.tolist()) == list(range(n))


def test_sharded_iteration_matches_global():
    # a rank's events equal the globally-indexed events at its strided indices
    full = SyntheticSource(num_events=12, detector_name="epix100")
    rank1 = SyntheticSource(num_events=12, detector_name="epix100", shard_rank=1, num_shards=3)
    got = [d for d, _ in rank1.iter_events()]
    want = [full.event(i)[0] for i in (1, 4, 7, 10)]
    assert len(got) == 4
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


def test_start_event_resume_cursor():
    src = SyntheticSource(num_events=10, detector_name="epix100", start_event=6)
    assert list(src.shard_event_indices()) == [6, 7, 8, 9]


def test_raw_mode_has_pedestal():
    src = SyntheticSource(**SMALL)
    raw, _ = src.event(0, RetrievalMode.RAW)
    calib, _ = src.event(0, RetrievalMode.CALIB)
    # raw is in ADUs sitting on a ~100 ADU pedestal; calib is ~0-background photons
    assert raw.mean() > 50
    assert abs(float(np.median(calib))) < 1.0


def test_image_mode_2d():
    src = SyntheticSource(**SMALL)
    img, _ = src.event(0, RetrievalMode.IMAGE)
    assert img.ndim == 2


def test_bad_pixel_fraction():
    src = SyntheticSource(num_events=1)  # epix10k2M default
    mask = src.create_bad_pixel_mask()
    frac_bad = 1.0 - mask.mean()
    assert 0.001 < frac_bad < 0.006


def test_replay_roundtrip(tmp_path):
    frames = np.random.default_rng(0).random((6, 2, 8, 8)).astype(np.float32)
    energy = np.linspace(8, 12, 6)
    path = tmp_path / "run.npz"
    np.savez(path, frames=frames, photon_energy=energy)
    src = ReplaySource(str(path))
    events = list(src.iter_events())
    assert len(events) == 6
    np.testing.assert_array_equal(events[2][0], frames[2])
    assert events[2][1] == pytest.approx(energy[2])


def test_replay_sharded(tmp_path):
    frames = np.zeros((10, 1, 4, 4), np.float32)
    path = tmp_path / "run.npy"
    np.save(path, frames)
    src = ReplaySource(str(path), shard_rank=1, num_shards=4)
    assert len(src) == len(list(src.iter_events()))


def test_open_source_dispatch(tmp_path):
    assert isinstance(open_source("synthetic", 1, "epix100"), SyntheticSource)
    np.save(tmp_path / "x.npy", np.zeros((2, 1, 4, 4), np.float32))
    assert isinstance(open_source(f"replay:{tmp_path}/x.npy", 1, "epix100"), ReplaySource)
    with pytest.raises(RuntimeError, match="psana"):
        open_source("mfxl1038923", 58, "epix10k2M")


def test_replay_npz_uncompressed_is_true_mmap(tmp_path):
    """np.savez members are ZIP_STORED: the replay source must map them
    directly (no whole-member decompression — the 86 GB >RAM replay case)."""
    frames = np.random.default_rng(1).random((5, 2, 8, 8)).astype(np.float32)
    path = tmp_path / "big.npz"
    np.savez(path, frames=frames, photon_energy=np.full(5, 9.5))
    src = ReplaySource(str(path))
    import mmap as _mmap

    arr = src._frames
    while getattr(arr, "base", None) is not None and not isinstance(arr, _mmap.mmap):
        if isinstance(arr, np.memmap):
            break
        arr = arr.base
    assert isinstance(arr, (np.memmap, _mmap.mmap)), type(arr)
    events = list(src.iter_events())
    assert len(events) == 5
    np.testing.assert_array_equal(events[3][0], frames[3])


def test_replay_npz_compressed_still_works(tmp_path):
    frames = np.random.default_rng(2).random((4, 1, 4, 4)).astype(np.float32)
    path = tmp_path / "c.npz"
    np.savez_compressed(path, frames=frames)
    src = ReplaySource(str(path))
    events = list(src.iter_events())
    assert len(events) == 4
    np.testing.assert_array_equal(events[1][0], frames[1])


def test_hit_fraction_labels():
    """hit_fraction makes a labeled hit-finding corpus: 'miss' events
    plant zero peaks (empty truth), hits plant as before; deterministic
    per event, and both classes occur at 0.5."""
    from psana_ray_tpu.sources import SyntheticSource

    src = SyntheticSource(
        num_events=40, detector_name="smoke_a", seed=3, hit_fraction=0.5
    )
    labels = []
    for i in range(40):
        _, _, truth = src.event_with_truth(i)
        labels.append(1 if len(truth) else 0)
        # determinism: same event, same class and frame
        d1, e1, t1 = src.event_with_truth(i)
        d2, e2, t2 = src.event_with_truth(i)
        np.testing.assert_array_equal(d1, d2)
        assert len(t1) == len(t2)
    assert 0 < sum(labels) < 40  # both classes present

    all_hit = SyntheticSource(
        num_events=8, detector_name="smoke_a", seed=3, hit_fraction=1.0
    )
    all_miss = SyntheticSource(
        num_events=8, detector_name="smoke_a", seed=3, hit_fraction=0.0
    )
    for i in range(8):
        assert len(all_hit.event_with_truth(i)[2]) > 0
        assert len(all_miss.event_with_truth(i)[2]) == 0
    # miss frames still carry background (not all-zero)
    assert float(np.abs(all_miss.event(0)[0]).sum()) > 0


def test_hit_fraction_default_keeps_frames_identical():
    """hit_fraction=None must not consume extra rng draws — frames from
    a default source are bit-identical to the pre-knob generator (replay
    determinism across versions)."""
    from psana_ray_tpu.sources import SyntheticSource

    a = SyntheticSource(num_events=4, detector_name="smoke_a", seed=9)
    b = SyntheticSource(
        num_events=4, detector_name="smoke_a", seed=9, hit_fraction=None
    )
    for i in range(4):
        np.testing.assert_array_equal(a.event(i)[0], b.event(i)[0])


def test_hit_fraction_validated():
    from psana_ray_tpu.sources import SyntheticSource

    with pytest.raises(ValueError, match="hit_fraction"):
        SyntheticSource(detector_name="smoke_a", hit_fraction=1.5)
