"""Reusable fault-injection TCP proxies for transport/durability tests.

Grown out of the ad-hoc delay-line proxy test_tcp_stream.py carried
since ISSUE 5 (now imported from here): a recovery test should INJECT
its failure — kill the wire at an exact byte, tear a write in half,
stall a direction — instead of reaching into server internals or
killing sockets it happens to hold. Both proxies listen on an ephemeral
local port and forward to a destination.

:class:`DelayProxy`
    Fixed one-way latency, unlimited bandwidth (per-direction delay
    lines with chunk coalescing) — models RTT, not throughput.

:class:`ThrottleProxy`
    Token-bucket bytes/s cap per direction, zero added latency —
    models BANDWIDTH, not RTT (the wire-compression A/B's honest
    adversary: a 50 MB/s tunnel does not care how many round trips
    you saved). Each direction has its own bucket, like a full-duplex
    link.

:class:`DiskFaultInjector`
    The storage sibling (ISSUE 11): arms the patchable disk-fault hook
    in :mod:`psana_ray_tpu.storage.log` so segment appends/fsyncs raise
    ``OSError`` (default ``ENOSPC``) after N successful ops — a failing
    or full durable disk, injected without touching a real filesystem.
    Context manager; the hook is process-wide, so use it around
    in-process servers only.

:func:`arrival_schedule` / :class:`OpenLoopLoad`
    Open-loop burst generation (ISSUE 12): DETERMINISTIC arrival-time
    schedules (steady / burst / ramp profiles) plus a driver that fires
    per-tenant ``submit`` callbacks at those times regardless of how
    the system under test is coping — an open-loop source keeps
    offering at the configured rate while the server drowns, which is
    exactly the adversary an admission-controlled gateway exists for
    (a closed-loop client would politely back off and hide the
    overload). Reused by the bench ``serving`` section and the gateway
    tests.

:class:`FaultProxy`
    Byte-counting fault injector. Faults are armed per direction
    (``"up"`` = client->server, ``"down"`` = server->client):

    - ``kill_at(direction, nbytes)`` — forward exactly ``nbytes`` more,
      then sever BOTH sides of every connection (a crash mid-message:
      the peer sees a clean-cut byte stream, exactly what a kill -9 of
      the remote produces on the wire);
    - ``torn_write_at(direction, nbytes, keep)`` — at the trigger,
      forward only ``keep`` bytes of the in-flight chunk, then sever
      (a torn write: the receiver holds a half-record);
    - ``stall_at(direction, nbytes, stall_s)`` — pause forwarding that
      direction for ``stall_s`` (connections stay up: models a wedged
      peer / network brownout, the stall-detector's jurisdiction);
    - ``kill_now()`` — sever everything immediately.

    Counting is cumulative across connections per direction, so "kill
    after the 3rd frame" is ``kill_at("up", 3 * frame_wire_bytes)``
    regardless of reconnects. One fault per direction at a time; re-arm
    after it fires (``fired`` tells you it did).
"""

from __future__ import annotations

import errno
import os
import socket
import threading
import time
from collections import deque


class DiskFaultInjector:
    """Arm the storage layer's patchable disk-fault hook: after
    ``ok_ops`` successful matching ops, every further matching op
    raises ``OSError(err)`` until :meth:`disarm` (or context exit).

    ``ops`` filters which hook sites fault (``"append"``, ``"sync"``).
    The durable stack is expected to degrade LOUDLY — ``disk_fault``
    flight breadcrumb + DURABLE counter + an 'E' answer to the
    producer — and the serving loop must survive (pinned by
    tests/test_replication.py)."""

    def __init__(self, ok_ops: int = 0, err: int = errno.ENOSPC,
                 ops=("append", "sync")):
        self.ok_ops = ok_ops
        self.err = err
        self.ops = tuple(ops)
        self.fired = 0
        self._seen = 0
        self._lock = threading.Lock()
        self._armed = True

    def __call__(self, op: str) -> None:
        with self._lock:
            if not self._armed or op not in self.ops:
                return
            self._seen += 1
            if self._seen <= self.ok_ops:
                return
            self.fired += 1
        raise OSError(self.err, f"{os.strerror(self.err)} (injected, op={op})")

    def disarm(self) -> None:
        with self._lock:
            self._armed = False

    def __enter__(self) -> "DiskFaultInjector":
        from psana_ray_tpu.storage.log import set_disk_fault_hook

        set_disk_fault_hook(self)
        return self

    def __exit__(self, *exc) -> None:
        from psana_ray_tpu.storage.log import set_disk_fault_hook

        set_disk_fault_hook(None)


class DelayProxy:
    """TCP proxy adding a fixed one-way latency WITHOUT limiting
    bandwidth: each received chunk enters a per-direction delay line and
    is released ``delay_s`` later (a sleep-per-chunk pump would serialize
    chunks and model bandwidth, not latency)."""

    def __init__(self, dst_host: str, dst_port: int, delay_s: float):
        self.delay_s = delay_s
        self._dst = (dst_host, dst_port)
        self._stop = threading.Event()
        self._socks = []
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("127.0.0.1", 0))
        self._lsock.listen(16)
        self.port = self._lsock.getsockname()[1]
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        self._lsock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                dst = socket.create_connection(self._dst, timeout=5.0)
            except OSError:
                conn.close()
                continue
            for s in (conn, dst):
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks += [conn, dst]
            self._pipe(conn, dst)
            self._pipe(dst, conn)

    def _pipe(self, src, dst):
        line = deque()  # (deliver_at, chunk)
        cond = threading.Condition()
        eof = [False]

        def rx():
            try:
                while not self._stop.is_set():
                    data = src.recv(1 << 20)  # big chunks: the proxy must
                    # model latency, not become the bandwidth bottleneck
                    if not data:
                        break
                    with cond:
                        line.append((time.monotonic() + self.delay_s, data))
                        cond.notify()
            except OSError:
                pass
            with cond:
                eof[0] = True
                cond.notify()

        def tx():
            try:
                while True:
                    with cond:
                        while not line and not eof[0]:
                            if self._stop.is_set():
                                return
                            cond.wait(timeout=0.2)
                        if not line:
                            break
                        at, data = line.popleft()
                        lag = at - time.monotonic()
                        if lag <= 0:
                            # coalesce every already-ripe chunk into one
                            # send: per-chunk wakeups would quantize the
                            # relay to the scheduler tick and turn the
                            # latency model into a bandwidth bottleneck
                            ripe = [data]
                            now = time.monotonic()
                            while line and line[0][0] <= now:
                                ripe.append(line.popleft()[1])
                            data = b"".join(ripe) if len(ripe) > 1 else data
                            lag = 0.0
                    if lag > 0:
                        time.sleep(lag)
                    dst.sendall(data)
            except OSError:
                pass
            try:
                dst.shutdown(socket.SHUT_WR)
            except OSError:
                pass

        threading.Thread(target=rx, daemon=True).start()
        threading.Thread(target=tx, daemon=True).start()

    def close(self):
        self._stop.set()
        for s in [self._lsock, *self._socks]:
            try:
                s.close()
            except OSError:
                pass


class ThrottleProxy:
    """TCP proxy capping each direction at ``bytes_per_s`` with a token
    bucket (burst = ``burst_s`` seconds of rate): chunks are forwarded
    in bounded slices, each waiting for its tokens — throughput
    converges to the cap from below, with no artificial latency while
    tokens remain. One bucket per direction, shared across every
    proxied connection (the directions of one physical link contend
    with themselves, exactly like a real full-duplex tunnel)."""

    # forwarding granularity: big enough that pacing sleeps are several
    # ms each (sub-ms sleeps on a loaded 2-core box wake late and
    # throttle BELOW the cap — the proxy must model the link, not the
    # scheduler), small enough that the burst bucket still smooths it
    _SLICE = 256 * 1024
    _MIN_SLEEP_S = 0.004  # debts below this accrue in the bucket instead

    def __init__(self, dst_host: str, dst_port: int, bytes_per_s: float, burst_s: float = 0.25):
        self.bytes_per_s = float(bytes_per_s)
        self._burst = self.bytes_per_s * burst_s
        self._dst = (dst_host, dst_port)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._socks = []  # guarded-by: _lock
        now = time.monotonic()
        # direction -> [tokens, last_refill]
        self._bucket = {"up": [self._burst, now], "down": [self._burst, now]}  # guarded-by: _lock
        self._bytes = {"up": 0, "down": 0}  # guarded-by: _lock
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("127.0.0.1", 0))
        self._lsock.listen(16)
        self.port = self._lsock.getsockname()[1]
        threading.Thread(target=self._accept, daemon=True).start()

    def bytes_forwarded(self, direction: str) -> int:
        with self._lock:
            return self._bytes[direction]

    def _take(self, direction: str, n: int) -> float:
        """Deduct ``n`` tokens; returns how long the caller must sleep
        before forwarding (0 when the bucket covers the chunk)."""
        with self._lock:
            bucket = self._bucket[direction]
            now = time.monotonic()
            bucket[0] = min(
                self._burst, bucket[0] + (now - bucket[1]) * self.bytes_per_s
            )
            bucket[1] = now
            bucket[0] -= n
            wait = -bucket[0] / self.bytes_per_s if bucket[0] < 0 else 0.0
            self._bytes[direction] += n
            return wait

    def _accept(self):
        self._lsock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                dst = socket.create_connection(self._dst, timeout=5.0)
            except OSError:
                conn.close()
                continue
            for s in (conn, dst):
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._socks += [conn, dst]
            threading.Thread(
                target=self._pump, args=(conn, dst, "up"), daemon=True
            ).start()
            threading.Thread(
                target=self._pump, args=(dst, conn, "down"), daemon=True
            ).start()

    def _pump(self, src, dst, direction: str):
        try:
            while not self._stop.is_set():
                data = src.recv(self._SLICE)
                if not data:
                    break
                wait = self._take(direction, len(data))
                if wait >= self._MIN_SLEEP_S:  # smaller debts stay banked
                    time.sleep(wait)
                dst.sendall(data)
        except OSError:
            pass
        for s in (src, dst):
            try:
                s.shutdown(socket.SHUT_WR)
            except OSError:
                pass

    def close(self):
        self._stop.set()
        with self._lock:
            socks, self._socks = self._socks, []
        for s in [self._lsock, *socks]:
            try:
                s.close()
            except OSError:
                pass


def arrival_schedule(
    profile: str,
    rate_hz: float,
    duration_s: float,
    burst_factor: float = 4.0,
    period_s: float = 1.0,
    ramp_to_hz: float = 0.0,
):
    """Deterministic open-loop arrival offsets (seconds from start),
    sorted ascending. ``rate_hz`` is the MEAN rate for every profile,
    so A/B rows at different shapes offer the same total work:

    - ``steady``: uniform spacing at ``rate_hz``;
    - ``burst``: square wave with period ``period_s`` — all of each
      period's arrivals land inside its first ``1/burst_factor``
      fraction (instantaneous rate ``burst_factor * rate_hz``, then
      silence): the queue-dwell adversary;
    - ``ramp``: rate climbs linearly to ``ramp_to_hz`` (default
      ``2 * rate_hz``), starting low enough that the MEAN stays
      ``rate_hz``: the knee-finding shape.
    """
    if rate_hz <= 0 or duration_s <= 0:
        return []
    n = int(rate_hz * duration_s)
    if profile == "steady":
        return [i / rate_hz for i in range(n)]
    if profile == "burst":
        if burst_factor <= 1.0:
            raise ValueError(f"burst_factor must exceed 1, got {burst_factor}")
        if period_s <= 0:
            raise ValueError(f"period_s must be positive, got {period_s}")
        # fractional per-period arithmetic: int() truncation here would
        # realize a different mean rate than documented (and collapse
        # to one arrival/period when rate_hz * period_s < 2)
        per_period = rate_hz * period_s
        on_s = period_s / burst_factor
        out = []
        for i in range(n):
            period_idx = int(i // per_period)
            k = i - period_idx * per_period
            out.append(period_idx * period_s + (k / per_period) * on_s)
        return out
    if profile == "ramp":
        r1 = ramp_to_hz or 2.0 * rate_hz
        # mean rate == rate_hz: start low enough that the ramp averages
        # out (r0 + r1) / 2 == rate_hz
        r0 = max(0.0, 2.0 * rate_hz - r1)
        t_ = duration_s
        out = []
        for i in range(n):
            # invert the cumulative count N(t) = r0 t + (r1-r0) t^2 / 2T
            a = (r1 - r0) / (2.0 * t_)
            if a <= 0:
                out.append(i / rate_hz)
                continue
            # solve a t^2 + r0 t - i = 0 for t >= 0
            t = (-r0 + (r0 * r0 + 4.0 * a * i) ** 0.5) / (2.0 * a)
            out.append(min(t, t_))
        return out
    raise ValueError(f"profile must be steady|burst|ramp, got {profile!r}")


class OpenLoopLoad:
    """Fire per-tenant schedules against ``submit(tenant)`` in real
    time, OPEN-loop: arrivals that fell due while the driver was asleep
    (scheduler jitter on a loaded box) are fired immediately in catch-up
    — the offered count over the run is exactly the schedule's, never
    throttled by the system under test.

    ``schedules`` maps tenant name -> arrival offsets (seconds; from
    :func:`arrival_schedule`). ``run()`` blocks until every schedule
    drains and returns ``{tenant: offered_count}``; ``start()`` +
    ``join()`` split that for concurrent measurement."""

    def __init__(self, submit, schedules: dict):
        self._submit = submit
        self._schedules = {t: sorted(s) for t, s in schedules.items()}
        self._threads = []
        self.offered = {t: 0 for t in schedules}

    def _drive(self, tenant: str, schedule):
        t0 = time.monotonic()
        n = 0
        for off in schedule:
            lag = (t0 + off) - time.monotonic()
            if lag > 0:
                time.sleep(lag)
            self._submit(tenant)
            n += 1
        self.offered[tenant] = n

    def start(self) -> "OpenLoopLoad":
        for tenant, schedule in self._schedules.items():
            t = threading.Thread(
                target=self._drive, args=(tenant, schedule),
                daemon=True, name=f"openloop-{tenant}",
            )
            self._threads.append(t)
            t.start()
        return self

    def join(self, timeout_s: float = 600.0) -> dict:
        deadline = time.monotonic() + timeout_s
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        return dict(self.offered)

    def run(self, timeout_s: float = 600.0) -> dict:
        return self.start().join(timeout_s)


class _Fault:
    __slots__ = ("kind", "at_bytes", "keep", "stall_s", "fired")

    def __init__(self, kind, at_bytes, keep=0, stall_s=0.0):
        self.kind = kind  # "kill" | "torn" | "stall"
        self.at_bytes = at_bytes
        self.keep = keep
        self.stall_s = stall_s
        self.fired = False


class FaultProxy:
    """Byte-counting fault injector — see the module docstring."""

    def __init__(self, dst_host: str, dst_port: int):
        self._dst = (dst_host, dst_port)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._socks = []  # guarded-by: _lock
        self._bytes = {"up": 0, "down": 0}  # guarded-by: _lock
        self._faults = {"up": None, "down": None}  # guarded-by: _lock
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("127.0.0.1", 0))
        self._lsock.listen(16)
        self.port = self._lsock.getsockname()[1]
        threading.Thread(target=self._accept, daemon=True).start()

    # -- fault arming ------------------------------------------------------
    def kill_at(self, direction: str, nbytes: int) -> "_Fault":
        return self._arm(direction, _Fault("kill", nbytes))

    def torn_write_at(self, direction: str, nbytes: int, keep: int) -> "_Fault":
        return self._arm(direction, _Fault("torn", nbytes, keep=keep))

    def stall_at(self, direction: str, nbytes: int, stall_s: float) -> "_Fault":
        return self._arm(direction, _Fault("stall", nbytes, stall_s=stall_s))

    def _arm(self, direction: str, fault: _Fault) -> _Fault:
        if direction not in ("up", "down"):
            raise ValueError(f"direction must be up|down, got {direction!r}")
        with self._lock:
            self._faults[direction] = fault
        return fault

    def bytes_forwarded(self, direction: str) -> int:
        with self._lock:
            return self._bytes[direction]

    def kill_now(self) -> None:
        """Sever every proxied connection immediately (both sides)."""
        with self._lock:
            socks, self._socks = self._socks, []
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    # -- plumbing ----------------------------------------------------------
    def _accept(self):
        self._lsock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                dst = socket.create_connection(self._dst, timeout=5.0)
            except OSError:
                conn.close()
                continue
            for s in (conn, dst):
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._socks += [conn, dst]
            threading.Thread(
                target=self._pump, args=(conn, dst, "up"), daemon=True
            ).start()
            threading.Thread(
                target=self._pump, args=(dst, conn, "down"), daemon=True
            ).start()

    def _pump(self, src, dst, direction: str):
        try:
            while not self._stop.is_set():
                data = src.recv(1 << 16)
                if not data:
                    break
                send = data
                fire = None
                stall = 0.0
                with self._lock:
                    fault = self._faults[direction]
                    counted = self._bytes[direction]
                    if fault is not None and not fault.fired and (
                        counted + len(data) >= fault.at_bytes
                    ):
                        fault.fired = True
                        if fault.kind == "kill":
                            send = data[: max(0, fault.at_bytes - counted)]
                            fire = "kill"
                        elif fault.kind == "torn":
                            cut = max(0, fault.at_bytes - counted)
                            send = data[: cut + fault.keep]
                            fire = "kill"  # a torn write severs after it
                        else:  # stall: forward intact, then pause
                            stall = fault.stall_s
                    self._bytes[direction] += len(send)
                if send:
                    dst.sendall(send)
                if fire == "kill":
                    self.kill_now()
                    return
                if stall:
                    time.sleep(stall)
        except OSError:
            pass
        for s in (src, dst):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def close(self):
        self._stop.set()
        self.kill_now()
        try:
            self._lsock.close()
        except OSError:
            pass
