"""Native shared-memory ring: contract parity, wire payloads, true
cross-process operation, fault propagation."""

import multiprocessing as mp
import os
import time

import numpy as np
import pytest

from psana_ray_tpu.records import EndOfStream, FrameRecord, is_eos
from psana_ray_tpu.transport import EMPTY, TransportClosed
from psana_ray_tpu.transport.shm_ring import ShmRingBuffer, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable"
)


@pytest.fixture
def ring(request):
    name = f"test_{request.node.name[:40]}_{os.getpid()}"
    r = ShmRingBuffer.create(name, maxsize=8, slot_bytes=256 * 1024)
    yield r
    r.destroy()


class TestContractParity:
    def test_fifo_and_typed_empty(self, ring):
        assert ring.get() is EMPTY
        assert ring.put({"a": 1})
        assert ring.put({"b": 2})
        assert ring.get() == {"a": 1}
        assert ring.get() == {"b": 2}
        assert ring.get() is EMPTY

    def test_full_returns_false(self, ring):
        n = 0
        while ring.put(n):
            n += 1
        assert n == ring.maxsize
        assert ring.size() == ring.maxsize
        assert ring.stats()["puts_rejected"] >= 1
        assert ring.get() == 0  # nothing lost, order kept

    def test_frame_record_payload(self, ring):
        panels = np.arange(2 * 8 * 16, dtype=np.float32).reshape(2, 8, 16)
        ring.put(FrameRecord(3, 41, panels, 9.7))
        out = ring.get()
        assert isinstance(out, FrameRecord)
        assert (out.shard_rank, out.event_idx) == (3, 41)
        np.testing.assert_array_equal(out.panels, panels)
        ring.put(EndOfStream(total_events=42))
        assert is_eos(ring.get())

    def test_oversized_message_rejected(self, ring):
        with pytest.raises(ValueError, match="slot size"):
            ring.put(FrameRecord(0, 0, np.zeros((4, 256, 256), np.float32), 1.0))
        assert ring.size() == 0

    def test_close_raises_on_both_sides(self, ring):
        ring.put(1)
        ring.close()
        with pytest.raises(TransportClosed):
            ring.put(2)
        with pytest.raises(TransportClosed):
            ring.get()

    def test_get_wait_timeout(self, ring):
        t0 = time.monotonic()
        assert ring.get_wait(timeout=0.05) is EMPTY
        assert time.monotonic() - t0 >= 0.04

    def test_get_batch(self, ring):
        for i in range(6):
            ring.put(i)
        assert ring.get_batch(4, timeout=0.1) == [0, 1, 2, 3]
        assert ring.get_batch(4, timeout=0.1) == [4, 5]


def _producer_proc(name, n, shard_rank):
    ring = ShmRingBuffer.attach(name, retries=10, interval_s=0.1)
    for i in range(shard_rank, n, 2):
        rec = FrameRecord(shard_rank, i, np.full((1, 16, 16), float(i), np.float32), 1.0)
        while not ring.put(rec):
            time.sleep(0.0005)
    ring.disconnect()


class TestCrossProcess:
    def test_two_producer_processes_one_consumer(self):
        name = f"xproc_{os.getpid()}"
        ring = ShmRingBuffer.create(name, maxsize=4, slot_bytes=64 * 1024)
        try:
            ctx = mp.get_context("spawn")  # real separate processes
            n = 20
            procs = [
                ctx.Process(target=_producer_proc, args=(name, n, r)) for r in range(2)
            ]
            for p in procs:
                p.start()
            got = []
            deadline = time.monotonic() + 60
            while len(got) < n and time.monotonic() < deadline:
                item = ring.get_wait(timeout=1.0)
                if item is not EMPTY:
                    got.append(item)
            for p in procs:
                p.join(timeout=10)
                assert p.exitcode == 0
            assert sorted(r.event_idx for r in got) == list(range(n))
            # payload integrity across the process boundary
            for r in got:
                assert float(r.panels[0, 0, 0]) == float(r.event_idx)
        finally:
            ring.destroy()

    def test_attach_timeout(self):
        from psana_ray_tpu.transport.registry import RendezvousTimeout

        with pytest.raises(RendezvousTimeout):
            ShmRingBuffer.attach(f"never_{os.getpid()}", retries=2, interval_s=0.05)


def _crash_mid_reserve(name):
    """Attach, claim a slot via reserve, then die WITHOUT committing —
    the failure the stall watchdog exists to detect."""
    import ctypes
    import signal

    from psana_ray_tpu.transport.shm_ring import _load_lib

    ring = ShmRingBuffer.attach(name, retries=5, interval_s=0.2)
    lib = _load_lib()
    ptr, ticket = ctypes.c_void_p(), ctypes.c_uint64()
    rc = lib.shmring_reserve(ring._h, ctypes.byref(ptr), ctypes.byref(ticket))
    assert rc == 1
    os.kill(os.getpid(), signal.SIGKILL)  # no commit, no cleanup


class TestWedgeDetection:
    """A peer that dies between claim and commit/release must surface as a
    loud TransportWedged, not an indefinite EMPTY/full stall (round-2
    VERDICT weak #6; native/shmring.cpp StallWatch)."""

    def test_sigkilled_producer_wedges_consumer_loudly(self):
        from psana_ray_tpu.transport import TransportWedged

        name = f"wedge_{os.getpid()}"
        ring = ShmRingBuffer.create(name, maxsize=4, slot_bytes=4096)
        ring.set_stall_timeout(0.3)
        try:
            ctx = mp.get_context("spawn")
            p = ctx.Process(target=_crash_mid_reserve, args=(name,))
            p.start()
            p.join(timeout=30)
            assert p.exitcode == -9  # SIGKILL, slot left claimed

            with pytest.raises(TransportWedged, match="producer.*crashed"):
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    ring.get()
                    time.sleep(0.01)
            # the wait for the error stayed near the configured window
        finally:
            ring.destroy()

    def test_unreleased_consumer_wedges_producer_loudly(self):
        import ctypes

        from psana_ray_tpu.transport import TransportWedged
        from psana_ray_tpu.transport.shm_ring import _load_lib

        name = f"wedgep_{os.getpid()}"
        ring = ShmRingBuffer.create(name, maxsize=2, slot_bytes=4096)
        ring.set_stall_timeout(0.3)
        try:
            assert ring.put(b"a") and ring.put(b"b")  # full
            # claim the tail slot like a consumer, then "crash" (no release)
            lib = _load_lib()
            ptr, ticket = ctypes.c_void_p(), ctypes.c_uint64()
            assert lib.shmring_acquire(ring._h, ctypes.byref(ptr), ctypes.byref(ticket)) >= 0

            with pytest.raises(TransportWedged, match="consumer.*crashed"):
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    ring.put(b"c")
                    time.sleep(0.01)
        finally:
            ring.destroy()

    def test_slow_peer_is_not_wedged(self, ring):
        # plain empty (no claim in flight) must never trip the watchdog
        ring.set_stall_timeout(0.1)
        time.sleep(0.3)
        assert ring.get() is EMPTY
        time.sleep(0.3)
        assert ring.get() is EMPTY


class TestVoidSlots:
    def test_get_skips_void_and_returns_next_item(self, ring):
        """A void slot (producer-side encode failure marker) must be
        consumed and skipped in one get() call — not reported as EMPTY
        while real items sit behind it (round-2 ADVICE)."""

        class Unpicklable:
            def __reduce__(self):
                raise RuntimeError("boom")

        with pytest.raises(Exception):
            ring.put(Unpicklable())  # pickle fails BEFORE reserve: no void
        # forge a void the way a mid-encode failure leaves one: reserve,
        # write the tag, commit len=1
        import ctypes

        from psana_ray_tpu.transport.codec import TAG_VOID
        from psana_ray_tpu.transport.shm_ring import _load_lib

        lib = _load_lib()
        ptr, ticket = ctypes.c_void_p(), ctypes.c_uint64()
        assert lib.shmring_reserve(ring._h, ctypes.byref(ptr), ctypes.byref(ticket)) == 1
        ctypes.memmove(ptr, TAG_VOID, 1)
        lib.shmring_commit(ring._h, ticket, 1)
        assert ring.put({"real": 1})

        assert ring.get() == {"real": 1}  # void consumed + skipped inline
        assert ring.stats()["voids_skipped"] == 1
        assert ring.get() is EMPTY


def test_wedge_propagates_as_error_through_batcher():
    """TransportWedged must NOT be absorbed by the batcher's clean
    closed-transport tail-flush: a wedge is data loss, not end of stream."""
    from psana_ray_tpu.infeed.batcher import batches_from_queue
    from psana_ray_tpu.transport import TransportWedged

    class WedgedQueue:
        def get_batch(self, n, timeout=None):
            raise TransportWedged("wedged")

    with pytest.raises(TransportWedged):
        list(batches_from_queue(WedgedQueue(), batch_size=4))


def test_drain_refuses_producers_serves_consumers():
    """Cross-process drain: a producer that bypasses any TCP server and
    writes straight into the ring must still be refused during drain,
    while consumers keep reading what's queued."""
    name = f"drain_{os.getpid()}"
    ring = ShmRingBuffer.create(name, maxsize=8, slot_bytes=4096)
    try:
        assert ring.put({"i": 0}) and ring.put({"i": 1})
        other = ShmRingBuffer.attach(name, retries=2, interval_s=0.1)
        ring.begin_drain()
        with pytest.raises(TransportClosed):
            other.put({"i": 2})  # attached producer sees the refusal
        assert ring.get() == {"i": 0}  # gets keep serving
        assert other.get() == {"i": 1}
        assert ring.get() is EMPTY
        other.disconnect()
    finally:
        ring.destroy()
