"""Native shared-memory ring: contract parity, wire payloads, true
cross-process operation, fault propagation."""

import multiprocessing as mp
import os
import time

import numpy as np
import pytest

from psana_ray_tpu.records import EndOfStream, FrameRecord, is_eos
from psana_ray_tpu.transport import EMPTY, TransportClosed
from psana_ray_tpu.transport.shm_ring import ShmRingBuffer, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable"
)


@pytest.fixture
def ring(request):
    name = f"test_{request.node.name[:40]}_{os.getpid()}"
    r = ShmRingBuffer.create(name, maxsize=8, slot_bytes=256 * 1024)
    yield r
    r.destroy()


class TestContractParity:
    def test_fifo_and_typed_empty(self, ring):
        assert ring.get() is EMPTY
        assert ring.put({"a": 1})
        assert ring.put({"b": 2})
        assert ring.get() == {"a": 1}
        assert ring.get() == {"b": 2}
        assert ring.get() is EMPTY

    def test_full_returns_false(self, ring):
        n = 0
        while ring.put(n):
            n += 1
        assert n == ring.maxsize
        assert ring.size() == ring.maxsize
        assert ring.stats()["puts_rejected"] >= 1
        assert ring.get() == 0  # nothing lost, order kept

    def test_frame_record_payload(self, ring):
        panels = np.arange(2 * 8 * 16, dtype=np.float32).reshape(2, 8, 16)
        ring.put(FrameRecord(3, 41, panels, 9.7))
        out = ring.get()
        assert isinstance(out, FrameRecord)
        assert (out.shard_rank, out.event_idx) == (3, 41)
        np.testing.assert_array_equal(out.panels, panels)
        ring.put(EndOfStream(total_events=42))
        assert is_eos(ring.get())

    def test_oversized_message_rejected(self, ring):
        with pytest.raises(ValueError, match="slot size"):
            ring.put(FrameRecord(0, 0, np.zeros((4, 256, 256), np.float32), 1.0))
        assert ring.size() == 0

    def test_close_raises_on_both_sides(self, ring):
        ring.put(1)
        ring.close()
        with pytest.raises(TransportClosed):
            ring.put(2)
        with pytest.raises(TransportClosed):
            ring.get()

    def test_get_wait_timeout(self, ring):
        t0 = time.monotonic()
        assert ring.get_wait(timeout=0.05) is EMPTY
        assert time.monotonic() - t0 >= 0.04

    def test_get_batch(self, ring):
        for i in range(6):
            ring.put(i)
        assert ring.get_batch(4, timeout=0.1) == [0, 1, 2, 3]
        assert ring.get_batch(4, timeout=0.1) == [4, 5]


def _producer_proc(name, n, shard_rank):
    ring = ShmRingBuffer.attach(name, retries=10, interval_s=0.1)
    for i in range(shard_rank, n, 2):
        rec = FrameRecord(shard_rank, i, np.full((1, 16, 16), float(i), np.float32), 1.0)
        while not ring.put(rec):
            time.sleep(0.0005)
    ring.disconnect()


class TestCrossProcess:
    def test_two_producer_processes_one_consumer(self):
        name = f"xproc_{os.getpid()}"
        ring = ShmRingBuffer.create(name, maxsize=4, slot_bytes=64 * 1024)
        try:
            ctx = mp.get_context("spawn")  # real separate processes
            n = 20
            procs = [
                ctx.Process(target=_producer_proc, args=(name, n, r)) for r in range(2)
            ]
            for p in procs:
                p.start()
            got = []
            deadline = time.monotonic() + 60
            while len(got) < n and time.monotonic() < deadline:
                item = ring.get_wait(timeout=1.0)
                if item is not EMPTY:
                    got.append(item)
            for p in procs:
                p.join(timeout=10)
                assert p.exitcode == 0
            assert sorted(r.event_idx for r in got) == list(range(n))
            # payload integrity across the process boundary
            for r in got:
                assert float(r.panels[0, 0, 0]) == float(r.event_idx)
        finally:
            ring.destroy()

    def test_attach_timeout(self):
        from psana_ray_tpu.transport.registry import RendezvousTimeout

        with pytest.raises(RendezvousTimeout):
            ShmRingBuffer.attach(f"never_{os.getpid()}", retries=2, interval_s=0.05)
