"""Multi-process data plane + kernel pass-through tests (ISSUE 17).

Covers the pieces separately, then the assembled fleet:

- :func:`queue_owner` — deterministic, respawn-stable rendezvous pinning
  and the per-worker balance proxy (message counts shard by queue name,
  so ownership spread IS the load spread for balanced queues);
- :class:`WorkerContext` — SCM_RIGHTS connection migration: the fd plus
  its JSON context arrive intact, bytes already in the kernel socket
  buffer travel with the fd, and malformed datagrams are dropped
  without leaking fds;
- :class:`WorkerSupervisor` — fork/reap/respawn with a STABLE worker id
  and a bounded stop;
- splice primitives — :class:`FileSpan` advance/materialize and the
  capability probes backing the sendfile pass-through;
- the spliced relay itself — lazy-spill durable queue served over TCP,
  plain connections splice (counters move), compressed connections
  downgrade to materialize, both roundtrip intact;
- the full ``--workers 2`` fleet over one real port: cross-worker
  routing, kill -9 of EVERY worker in turn with zero loss, and the CLI
  refusing the incompatible combinations loudly.
"""

import errno
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from psana_ray_tpu.records import FrameRecord, is_eos
from psana_ray_tpu.storage import DurableRingBuffer, SegmentLog
from psana_ray_tpu.transport import workers as workers_mod
from psana_ray_tpu.transport.splice import (
    SPLICE,
    FileSpan,
    fallback_errno,
    probe_report,
    sendfile_capable,
)
from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer
from psana_ray_tpu.transport.workers import (
    DEFAULT_QUEUE_WORKER,
    WorkerContext,
    WorkerSupervisor,
    queue_owner,
    resolve_port,
)

HAVE_REUSEPORT = hasattr(socket, "SO_REUSEPORT")
HAVE_FORK = hasattr(os, "fork")


def _rec(i, shape=(1, 16, 16)):
    return FrameRecord(0, i, np.full(shape, i % 4096, np.uint16), 9.5)


def _drain(client, want, timeout=2.0, deadline_s=30.0):
    out = []
    deadline = time.monotonic() + deadline_s
    while len(out) < want and time.monotonic() < deadline:
        batch = client.get_batch(64, timeout=timeout)
        if not batch:
            continue
        out.extend(r for r in batch if not is_eos(r))
        if any(is_eos(r) for r in batch):
            break
    return out


# ---------------------------------------------------------------------------
# rendezvous pinning
# ---------------------------------------------------------------------------


class TestQueueOwner:
    def test_single_worker_owns_everything(self):
        assert all(queue_owner("ns", f"q{i}", 1) == 0 for i in range(16))

    def test_default_queue_pin_is_worker_zero(self):
        # the implicit default queue bypasses queue_owner entirely —
        # the evloop routes it by this constant
        assert DEFAULT_QUEUE_WORKER == 0

    def test_pinning_is_deterministic_and_exact(self):
        # pinned literal map: these EXACT values are what makes respawn
        # stability real — a drift here silently re-homes live queues
        assert {f"q{i}": queue_owner("ns", f"q{i}", 2) for i in range(8)} == {
            "q0": 0, "q1": 0, "q2": 0, "q3": 1,
            "q4": 0, "q5": 1, "q6": 0, "q7": 0,
        }

    def test_pinning_survives_process_boundary(self):
        # blake2b rendezvous, not hash(): a fresh interpreter (its own
        # PYTHONHASHSEED) must compute the identical map, or two workers
        # would each believe they own the same queue
        here = {f"q{i}": queue_owner("ns", f"q{i}", 3) for i in range(16)}
        code = (
            "from psana_ray_tpu.transport.workers import queue_owner;"
            "print({f'q{i}': queue_owner('ns', f'q{i}', 3) for i in range(16)})"
        )
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, check=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert eval(out.stdout.strip()) == here

    def test_balance_proxy(self):
        # messages shard by queue name, so ownership spread over many
        # names is the per-worker message-count proxy: no worker may be
        # starved (each holds >= a quarter of its fair share)
        for n in (2, 3, 4):
            counts = [0] * n
            for i in range(64):
                counts[queue_owner("bench", f"stream-{i}", n)] += 1
            assert sum(counts) == 64
            assert min(counts) >= (64 // n) // 4, (n, counts)

    def test_owner_in_range(self):
        for n in (1, 2, 5, 8):
            for i in range(32):
                assert 0 <= queue_owner("x", f"n{i}", n) < n


# ---------------------------------------------------------------------------
# SCM_RIGHTS migration plumbing
# ---------------------------------------------------------------------------


@pytest.mark.skipif(
    not hasattr(socket, "recv_fds"), reason="needs socket.recv_fds"
)
class TestWorkerContext:
    def _two(self, tmp_path):
        c0 = WorkerContext(0, 2, str(tmp_path))
        c1 = WorkerContext(1, 2, str(tmp_path))
        return c0, c1

    def test_fd_migration_carries_context_and_buffered_bytes(self, tmp_path):
        c0, c1 = self._two(tmp_path)
        try:
            a, b = socket.socketpair()
            try:
                # bytes the client pipelined BEFORE migration sit in a's
                # kernel buffer — they must survive the fd's journey
                b.sendall(b"pipelined")
                ctx = {"kind": "op", "op": 7, "codec": "shuffle-rle"}
                c0.send_conn(1, a, ctx)
            finally:
                a.close()  # sender's copy; the datagram holds its own ref
            adopted = c1.recv_conns()
            assert len(adopted) == 1
            sock, got_ctx = adopted[0]
            try:
                assert got_ctx == ctx
                sock.settimeout(5.0)
                assert sock.recv(16) == b"pipelined"
                sock.sendall(b"reply")
                b.settimeout(5.0)
                assert b.recv(16) == b"reply"
            finally:
                sock.close()
                b.close()
        finally:
            c0.close()
            c1.close()
            workers_mod._CURRENT_WORKER_ID = None

    def test_bad_datagram_drops_without_adoption(self, tmp_path):
        c0, c1 = self._two(tmp_path)
        try:
            a, b = socket.socketpair()
            try:
                # garbage header: length field claims more than the blob
                import array

                c0._send_sock.sendmsg(
                    [b"\xff\xff\xff\xff"],
                    [(
                        socket.SOL_SOCKET,
                        socket.SCM_RIGHTS,
                        array.array("i", [a.fileno()]),
                    )],
                    0,
                    os.path.join(str(tmp_path), "worker-1.sock"),
                )
            finally:
                a.close()
            assert c1.recv_conns() == []
        finally:
            b.close()
            c0.close()
            c1.close()
            workers_mod._CURRENT_WORKER_ID = None

    def test_recv_on_empty_socket_returns_immediately(self, tmp_path):
        c0 = WorkerContext(0, 1, str(tmp_path))
        try:
            t0 = time.monotonic()
            assert c0.recv_conns() == []
            assert time.monotonic() - t0 < 0.5
        finally:
            c0.close()
            workers_mod._CURRENT_WORKER_ID = None

    def test_owner_of_matches_module_fn(self, tmp_path):
        c0 = WorkerContext(0, 4, str(tmp_path))
        try:
            for i in range(8):
                assert c0.owner_of("ns", f"q{i}") == queue_owner("ns", f"q{i}", 4)
        finally:
            c0.close()
            workers_mod._CURRENT_WORKER_ID = None


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not HAVE_FORK, reason="needs os.fork")
class TestWorkerSupervisor:
    @staticmethod
    def _sleeper(worker_id):
        while True:
            time.sleep(3600)

    def test_respawn_keeps_worker_id(self):
        sup = WorkerSupervisor(2, self._sleeper).start()
        try:
            pids = sup.pids()
            assert set(pids) == {0, 1}
            victim = pids[1]
            os.kill(victim, signal.SIGKILL)
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                cur = sup.pids()
                if set(cur) == {0, 1} and cur[1] != victim:
                    break
                time.sleep(0.05)
            cur = sup.pids()
            assert set(cur) == {0, 1}, cur
            assert cur[1] != victim
            assert cur[0] == pids[0]  # the survivor was not disturbed
            assert sup.snapshot()["respawns_total"] >= 1
        finally:
            sup.stop(timeout_s=10.0)
        assert sup.pids() == {}

    def test_stop_reaps_the_fleet(self):
        sup = WorkerSupervisor(2, self._sleeper).start()
        pids = list(sup.pids().values())
        sup.stop(timeout_s=10.0)
        assert sup.pids() == {}
        for pid in pids:
            # reaped: the pid no longer names our child (signal 0 probe)
            with pytest.raises(ProcessLookupError):
                os.kill(pid, 0)

    def test_rejects_zero_workers(self):
        with pytest.raises(ValueError):
            WorkerSupervisor(0, self._sleeper)


# ---------------------------------------------------------------------------
# kernel pass-through primitives
# ---------------------------------------------------------------------------


class TestSplicePrimitives:
    def test_filespan_advance_and_materialize(self, tmp_path):
        p = tmp_path / "seg"
        p.write_bytes(b"xxx" + b"payload-bytes" + b"yyy")
        with open(p, "rb") as f:
            span = FileSpan(f, 3, 13)
            assert span.materialize() == b"payload-bytes"
            span.advance(8)
            assert (span.pos, span.nbytes) == (11, 5)
            assert span.materialize() == b"bytes"
            # materialize is pread: the file's own position is untouched
            assert f.tell() == 0

    def test_fallback_errno_classification(self):
        assert fallback_errno(OSError(errno.EINVAL, "x"))
        assert fallback_errno(OSError(errno.ENOTSOCK, "x"))
        assert not fallback_errno(OSError(errno.EPIPE, "x"))
        assert not fallback_errno(OSError(errno.ECONNRESET, "x"))

    def test_probe_report_shape(self):
        rep = probe_report()
        assert set(rep) == {"sendfile", "msg_zerocopy"}
        assert all(isinstance(v, bool) for v in rep.values())
        # probe is memoized: second call agrees
        assert sendfile_capable() == rep["sendfile"]

    def test_resolve_port_is_bindable(self):
        if not HAVE_REUSEPORT:
            pytest.skip("needs SO_REUSEPORT")
        port = resolve_port("127.0.0.1", 0)
        assert 0 < port < 65536
        assert resolve_port("127.0.0.1", port) == port
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            s.bind(("127.0.0.1", port))
        finally:
            s.close()


# ---------------------------------------------------------------------------
# the spliced relay (single process)
# ---------------------------------------------------------------------------


def _lazy_spill_server(root, maxsize=500, ram_items=1):
    """Durable server whose queues spill almost immediately and deliver
    spilled records as un-read handles — every relayed frame past the
    tiny RAM window rides the sendfile path on plain connections."""

    def factory(ns, name, maxsize_):
        log = SegmentLog(
            os.path.join(str(root), f"{ns}__{name}"),
            name=name, segment_bytes=1 << 20, fsync="none",
        )
        return DurableRingBuffer(
            log, maxsize=maxsize_, name=name,
            ram_items=ram_items, lazy_spill=True,
        )

    return TcpQueueServer(
        factory("default", "default", maxsize),
        host="127.0.0.1", maxsize=maxsize, queue_factory=factory,
        group_store_path=os.path.join(str(root), "groups.json"),
    ).serve_background()


class TestSplicedRelay:
    def test_plain_connection_splices_and_roundtrips(self, tmp_path):
        srv = _lazy_spill_server(tmp_path)
        try:
            before = SPLICE.snapshot()
            prod = TcpQueueClient(
                "127.0.0.1", srv.port, namespace="ns", queue_name="sp",
                reconnect_tries=1,
            )
            for i in range(24):
                assert prod.put(_rec(i))
            cons = TcpQueueClient(
                "127.0.0.1", srv.port, namespace="ns", queue_name="sp",
                reconnect_tries=1,
            )
            got = _drain(cons, 24)
            assert [r.event_idx for r in got] == list(range(24))
            assert all(
                np.array_equal(r.panels, _rec(r.event_idx).panels) for r in got
            )
            after = SPLICE.snapshot()
            if sendfile_capable():
                # everything past the 1-item RAM window spilled, and a
                # plain connection moves spilled payloads by sendfile
                assert (
                    after["spliced_frames_total"]
                    > before["spliced_frames_total"]
                )
                assert after["spliced_bytes_total"] > before["spliced_bytes_total"]
            prod.disconnect()
            cons.disconnect()
        finally:
            srv.shutdown()

    def test_compressed_connection_materializes(self, tmp_path):
        srv = _lazy_spill_server(tmp_path)
        try:
            prod = TcpQueueClient(
                "127.0.0.1", srv.port, namespace="ns", queue_name="cz",
                reconnect_tries=1,
            )
            for i in range(12):
                assert prod.put(_rec(i))
            # a negotiated codec must re-encode the payload, so the
            # spilled bytes get read back into the interpreter — the
            # downgrade is invisible to the client
            cons = TcpQueueClient(
                "127.0.0.1", srv.port, namespace="ns", queue_name="cz",
                reconnect_tries=1, codec="shuffle-rle",
            )
            got = _drain(cons, 12)
            assert [r.event_idx for r in got] == list(range(12))
            assert all(
                np.array_equal(r.panels, _rec(r.event_idx).panels) for r in got
            )
            prod.disconnect()
            cons.disconnect()
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# the assembled fleet (--workers 2, real port, real processes)
# ---------------------------------------------------------------------------


def _worker_pids(parent_pid):
    """Direct children of ``parent_pid`` via /proc (the fleet's workers)."""
    out = []
    for d in os.listdir("/proc"):
        if not d.isdigit():
            continue
        try:
            with open(f"/proc/{d}/stat", "rb") as f:
                stat = f.read().decode("latin-1")
        except OSError:
            continue
        try:
            ppid = int(stat.rsplit(")", 1)[1].split()[1])
        except (IndexError, ValueError):
            continue
        if ppid == parent_pid:
            out.append(int(d))
    return sorted(out)


@pytest.mark.skipif(
    not (HAVE_REUSEPORT and HAVE_FORK and os.path.isdir("/proc")),
    reason="needs SO_REUSEPORT + fork + /proc",
)
class TestWorkersFleet:
    @staticmethod
    def _start(durable_dir, port_file, n=2):
        if os.path.exists(port_file):
            os.remove(port_file)
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "psana_ray_tpu.queue_server",
                "--workers", str(n), "--host", "127.0.0.1", "--port", "0",
                "--durable_dir", durable_dir,
                "--fsync", "batch", "--fsync_batch_n", "1",
                "--port_file", port_file, "--stall_poll_s", "0",
                "--queue_size", "500",
                "--segment_bytes", str(1 << 20),
            ],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            stderr=subprocess.DEVNULL,
        )
        deadline = time.monotonic() + 30
        while not os.path.exists(port_file):
            assert proc.poll() is None, "fleet parent died on startup"
            assert time.monotonic() < deadline, "no port file"
            time.sleep(0.05)
        return proc, int(open(port_file).read())

    @staticmethod
    def _stop(proc):
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)

    def test_cross_worker_routing_roundtrips(self, tmp_path):
        # q0 is pinned to worker 0, q3 to worker 1 (the exact map is a
        # test above): whichever worker the kernel's accept sharding
        # lands each connection on, migration must deliver both queues
        proc, port = self._start(str(tmp_path / "log"), str(tmp_path / "port"))
        try:
            for qname in ("q0", "q3"):
                prod = TcpQueueClient(
                    "127.0.0.1", port, namespace="ns", queue_name=qname,
                )
                for i in range(10):
                    assert prod.put(_rec(i))
                cons = TcpQueueClient(
                    "127.0.0.1", port, namespace="ns", queue_name=qname,
                )
                got = _drain(cons, 10)
                assert [r.event_idx for r in got] == list(range(10)), qname
                prod.disconnect()
                cons.disconnect()
        finally:
            self._stop(proc)

    def test_default_queue_roundtrips(self, tmp_path):
        proc, port = self._start(str(tmp_path / "log"), str(tmp_path / "port"))
        try:
            prod = TcpQueueClient("127.0.0.1", port)
            for i in range(10):
                assert prod.put(_rec(i))
            cons = TcpQueueClient("127.0.0.1", port)
            got = _drain(cons, 10)
            assert [r.event_idx for r in got] == list(range(10))
            prod.disconnect()
            cons.disconnect()
        finally:
            self._stop(proc)

    def test_kill9_each_worker_mid_stream_zero_loss(self, tmp_path):
        # the ISSUE 17 acceptance row: a consumer is MID-STREAM (has
        # consumed a prefix, holds a live connection) when every worker
        # is killed -9 in turn — so the queue's owner dies exactly
        # once, whichever worker that is. The supervisor respawns with
        # the same worker id, the durable log re-exposes everything
        # unacked, and the SAME client resumes via its reconnect
        # envelope: zero loss, dupes allowed (at-least-once, as ever)
        proc, port = self._start(str(tmp_path / "log"), str(tmp_path / "port"))
        try:
            prod = TcpQueueClient(
                "127.0.0.1", port, namespace="ns", queue_name="q3",
            )
            for i in range(20):
                assert prod.put(_rec(i))
            prod.disconnect()

            cons = TcpQueueClient(
                "127.0.0.1", port, namespace="ns", queue_name="q3",
            )
            first = cons.get_batch(6, timeout=10.0)
            assert len(first) == 6
            cons.size()  # implicit-ack: the committed offset moves

            initial = _worker_pids(proc.pid)
            assert len(initial) == 2, initial
            for victim in initial:
                os.kill(victim, signal.SIGKILL)
                deadline = time.monotonic() + 20
                while time.monotonic() < deadline:
                    cur = _worker_pids(proc.pid)
                    if victim not in cur and len(cur) == 2:
                        break
                    time.sleep(0.05)
                cur = _worker_pids(proc.pid)
                assert victim not in cur and len(cur) == 2, (victim, cur)

            # the same client keeps consuming: its reconnect envelope
            # rides out the dead connection and replays the OPEN.
            # Collect until the union is complete (dupes are legal —
            # at-least-once — so a fixed count would be wrong both ways)
            seen = {r.event_idx for r in first}
            deadline = time.monotonic() + 30
            while seen != set(range(20)) and time.monotonic() < deadline:
                for r in cons.get_batch(64, timeout=2.0):
                    seen.add(r.event_idx)
            assert seen == set(range(20)), (
                f"lost={sorted(set(range(20)) - seen)}"
            )
            cons.disconnect()
        finally:
            self._stop(proc)

    def test_cli_refuses_incompatible_planes(self, tmp_path):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for extra in (
            ["--shm", "ring"],
            [
                "--replicate_peers", "a:1,b:2", "--advertise", "a:1",
                "--durable_dir", str(tmp_path / "d"),
            ],
        ):
            out = subprocess.run(
                [
                    sys.executable, "-m", "psana_ray_tpu.queue_server",
                    "--workers", "2", "--port", "0",
                ] + extra,
                capture_output=True, cwd=root, timeout=60,
            )
            assert out.returncode == 2, out.stderr
            assert b"--workers" in out.stderr
