"""Peak extraction + CXI writer: synthetic frames with known peak positions
round-trip through find_peaks -> CXI (VERDICT r1 next-round item #10; the
reference names this mission in its packaging, setup.py:11, but ships none
of it)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psana_ray_tpu.models.peaks import (
    CxiWriter,
    find_peaks,
    read_cxi_peaks,
    unpad_peaks,
)


def _logits_with_peaks(h, w, centers, hot=8.0, cold=-8.0):
    """Logit map: `cold` everywhere, `hot` bumps at the given centers with
    a slightly dimmer ring so the local-max rule is actually exercised."""
    z = np.full((h, w), cold, np.float32)
    for (cy, cx) in centers:
        for dy in (-1, 0, 1):
            for dx in (-1, 0, 1):
                z[cy + dy, cx + dx] = hot - 2.0 * (abs(dy) + abs(dx))
    return z


class TestFindPeaks:
    def test_recovers_known_positions(self):
        centers = [(5, 7), (20, 33), (40, 12)]
        z = _logits_with_peaks(48, 48, centers)
        yx, score, n = jax.jit(find_peaks, static_argnums=(1,))(z[None], 16)
        assert int(n[0]) == 3
        got = {tuple(map(int, p)) for p in np.asarray(yx[0][: int(n[0])])}
        assert got == set(centers)
        assert np.all(np.asarray(score[0][:3]) > 0.9)

    def test_padded_fixed_shapes(self):
        z = _logits_with_peaks(32, 32, [(10, 10)])
        yx, score, n = find_peaks(z[None], max_peaks=8)
        assert yx.shape == (1, 8, 2) and score.shape == (1, 8)
        assert int(n[0]) == 1
        assert np.all(np.asarray(yx[0][1:]) == -1)  # padding marked

    def test_threshold_suppresses_background(self):
        z = np.zeros((1, 16, 16), np.float32)  # sigmoid=0.5 everywhere
        _, _, n = find_peaks(z, max_peaks=8, threshold=0.6)
        assert int(n[0]) == 0

    def test_plateau_yields_single_peak(self):
        z = np.full((1, 16, 16), -8.0, np.float32)
        z[0, 4:6, 4:6] = 6.0  # 2x2 plateau — tie-broken to ONE peak
        _, _, n = find_peaks(z, max_peaks=8)
        assert int(n[0]) == 1


class TestCxiRoundtrip:
    def test_roundtrip(self, tmp_path):
        centers = [(5, 7), (20, 33)]
        z = jnp.asarray(_logits_with_peaks(48, 48, centers)[None])
        yx, score, n = find_peaks(z, max_peaks=16)
        peaks = unpad_peaks(
            yx, score, n,
            event_idx=np.array([42]), shard_rank=np.array([3]),
            photon_energy=np.array([9.5]),
        )
        path = str(tmp_path / "peaks.cxi")
        with CxiWriter(path, max_peaks=16) as wtr:
            wtr.append(peaks)
            assert wtr.n_events == 1
        n_back, x, y, inten, ev = read_cxi_peaks(path)
        assert n_back[0] == 2
        got = {(int(yy), int(xx)) for yy, xx in zip(y[0][:2], x[0][:2])}
        assert got == set(centers)
        assert ev[0] == 42
        assert np.all(inten[0][:2] > 0.9)

    def test_append_batches(self, tmp_path):
        path = str(tmp_path / "multi.cxi")
        z = jnp.asarray(
            np.stack([_logits_with_peaks(32, 32, [(8, 8)]),
                      _logits_with_peaks(32, 32, [(4, 4), (20, 20)])])
        )
        yx, score, n = find_peaks(z, max_peaks=8)
        with CxiWriter(path, max_peaks=8) as wtr:
            wtr.append(unpad_peaks(yx, score, n))
            wtr.append(unpad_peaks(yx, score, n))
            assert wtr.n_events == 4
        n_back, *_ = read_cxi_peaks(path)
        assert list(n_back) == [1, 2, 1, 2]


class TestPeakMetrics:
    """peak_metrics + the synthetic source's planted ground truth
    (VERDICT r3 #5: the s2d quality numbers need an oracle)."""

    def test_event_with_truth_matches_event(self):
        from psana_ray_tpu.sources import SyntheticSource

        src = SyntheticSource(num_events=2, detector_name="smoke_a", seed=7)
        d1, e1 = src.event(1)
        d2, e2, truth = src.event_with_truth(1)
        np.testing.assert_array_equal(d1, d2)  # identical rng consumption
        assert e1 == e2
        assert truth.shape[1] == 4
        assert len(truth) >= 1
        p, h, w = src.spec.frame_shape
        assert (truth[:, 0] < p).all()
        assert (truth[:, 1] < h).all() and (truth[:, 2] < w).all()

    def test_truth_peaks_are_in_the_frame(self):
        from psana_ray_tpu.sources import SyntheticSource

        src = SyntheticSource(num_events=1, detector_name="smoke_a", seed=3)
        data, _, truth = src.event_with_truth(0)
        # a bright plant must actually be bright at its center
        bright = truth[truth[:, 3] > 200]
        for pi, cy, cx, amp in bright:
            v = data[int(pi), int(round(cy)), int(round(cx))]
            assert v > 50, (pi, cy, cx, amp, v)

    def test_metrics_exact_match(self):
        from psana_ray_tpu.models.peaks import peak_metrics

        pred_yx = np.full((1, 4, 2), -1, np.int32)
        pred_yx[0, :2] = [[10, 20], [30, 40]]
        truth = [np.asarray([[0, 10.4, 19.8, 100.0], [0, 29.9, 40.2, 100.0]])]
        m = peak_metrics(pred_yx, np.asarray([2]), truth, tolerance=2.0)
        assert m["recall"] == 1.0 and m["precision"] == 1.0

    def test_metrics_miss_and_false_positive(self):
        from psana_ray_tpu.models.peaks import peak_metrics

        pred_yx = np.full((1, 4, 2), -1, np.int32)
        pred_yx[0, :2] = [[10, 20], [90, 90]]  # second is spurious
        truth = [np.asarray([[0, 10, 20, 100.0], [0, 50, 50, 100.0]])]  # second missed
        m = peak_metrics(pred_yx, np.asarray([2]), truth, tolerance=2.0)
        assert m["recall"] == 0.5 and m["precision"] == 0.5

    def test_metrics_one_to_one_matching(self):
        from psana_ray_tpu.models.peaks import peak_metrics

        # two truth peaks near ONE prediction: only one may claim it
        pred_yx = np.full((1, 4, 2), -1, np.int32)
        pred_yx[0, :1] = [[10, 10]]
        truth = [np.asarray([[0, 10, 10, 100.0], [0, 11, 10, 100.0]])]
        m = peak_metrics(pred_yx, np.asarray([1]), truth, tolerance=3.0)
        assert m["n_matched"] == 1
        assert m["recall"] == 0.5 and m["precision"] == 1.0

    def test_min_amplitude_drops_subthreshold_truth(self):
        from psana_ray_tpu.models.peaks import peak_metrics

        pred_yx = np.full((1, 2, 2), -1, np.int32)
        truth = [np.asarray([[0, 10, 10, 20.0]])]  # weak plant, no prediction
        m = peak_metrics(pred_yx, np.asarray([0]), truth, min_amplitude=50.0)
        assert m["n_truth"] == 0 and m["recall"] == 0.0

    def test_split_truth_by_panel(self):
        from psana_ray_tpu.models.peaks import split_truth_by_panel

        truth = np.asarray([[0, 1, 2, 9.0], [2, 3, 4, 9.0], [0, 5, 6, 9.0]])
        parts = split_truth_by_panel(truth, 3)
        assert [len(p) for p in parts] == [2, 0, 1]

    def test_find_peaks_recovers_planted_truth(self):
        """End-to-end oracle check WITHOUT a model: sigmoid-space logits
        built directly from the calibrated frame must recover the bright
        planted peaks — validates the truth/metric plumbing itself."""
        import jax.numpy as jnp

        from psana_ray_tpu.models.peaks import (
            find_peaks,
            peak_metrics,
            split_truth_by_panel,
        )
        from psana_ray_tpu.sources import SyntheticSource

        # sparse plants: on the tiny smoke panels a dense field overlaps
        # into merged maxima, which tests geometry, not the plumbing
        src = SyntheticSource(
            num_events=1, detector_name="smoke_a", seed=11, peak_count=4
        )
        data, _, truth = src.event_with_truth(0)
        p = src.spec.frame_shape[0]
        # "perfect segmentation": logit rises with intensity, threshold at
        # 50 ADU. Scaled so sigmoid cannot saturate to exactly 1.0 in f32
        # — a saturated plateau ties every pixel and the raster tie-break
        # elects the plateau's corner, not the peak center
        logits = jnp.asarray((data - 50.0) * 0.01)[..., None]
        yx, score, n = find_peaks(logits, max_peaks=64, min_distance=2)
        m = peak_metrics(
            np.asarray(yx), np.asarray(n), split_truth_by_panel(truth, p),
            tolerance=3.0, min_amplitude=100.0,
        )
        assert m["recall"] >= 0.9, m

    def test_detection_of_ignored_truth_is_not_a_false_positive(self):
        from psana_ray_tpu.models.peaks import peak_metrics

        # one strong plant (matched) + one correctly-detected WEAK plant:
        # the weak detection must not count against precision
        pred_yx = np.full((1, 4, 2), -1, np.int32)
        pred_yx[0, :2] = [[10, 10], [40, 40]]
        truth = [np.asarray([[0, 10, 10, 500.0], [0, 40, 40, 60.0]])]
        m = peak_metrics(pred_yx, np.asarray([2]), truth, min_amplitude=100.0)
        assert m["n_truth"] == 1 and m["n_matched"] == 1
        assert m["precision"] == 1.0 and m["recall"] == 1.0
