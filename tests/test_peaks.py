"""Peak extraction + CXI writer: synthetic frames with known peak positions
round-trip through find_peaks -> CXI (VERDICT r1 next-round item #10; the
reference names this mission in its packaging, setup.py:11, but ships none
of it)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psana_ray_tpu.models.peaks import (
    CxiWriter,
    find_peaks,
    read_cxi_peaks,
    unpad_peaks,
)


def _logits_with_peaks(h, w, centers, hot=8.0, cold=-8.0):
    """Logit map: `cold` everywhere, `hot` bumps at the given centers with
    a slightly dimmer ring so the local-max rule is actually exercised."""
    z = np.full((h, w), cold, np.float32)
    for (cy, cx) in centers:
        for dy in (-1, 0, 1):
            for dx in (-1, 0, 1):
                z[cy + dy, cx + dx] = hot - 2.0 * (abs(dy) + abs(dx))
    return z


class TestFindPeaks:
    def test_recovers_known_positions(self):
        centers = [(5, 7), (20, 33), (40, 12)]
        z = _logits_with_peaks(48, 48, centers)
        yx, score, n = jax.jit(find_peaks, static_argnums=(1,))(z[None], 16)
        assert int(n[0]) == 3
        got = {tuple(map(int, p)) for p in np.asarray(yx[0][: int(n[0])])}
        assert got == set(centers)
        assert np.all(np.asarray(score[0][:3]) > 0.9)

    def test_padded_fixed_shapes(self):
        z = _logits_with_peaks(32, 32, [(10, 10)])
        yx, score, n = find_peaks(z[None], max_peaks=8)
        assert yx.shape == (1, 8, 2) and score.shape == (1, 8)
        assert int(n[0]) == 1
        assert np.all(np.asarray(yx[0][1:]) == -1)  # padding marked

    def test_threshold_suppresses_background(self):
        z = np.zeros((1, 16, 16), np.float32)  # sigmoid=0.5 everywhere
        _, _, n = find_peaks(z, max_peaks=8, threshold=0.6)
        assert int(n[0]) == 0

    def test_plateau_yields_single_peak(self):
        z = np.full((1, 16, 16), -8.0, np.float32)
        z[0, 4:6, 4:6] = 6.0  # 2x2 plateau — tie-broken to ONE peak
        _, _, n = find_peaks(z, max_peaks=8)
        assert int(n[0]) == 1


class TestCxiRoundtrip:
    def test_roundtrip(self, tmp_path):
        centers = [(5, 7), (20, 33)]
        z = jnp.asarray(_logits_with_peaks(48, 48, centers)[None])
        yx, score, n = find_peaks(z, max_peaks=16)
        peaks = unpad_peaks(
            yx, score, n,
            event_idx=np.array([42]), shard_rank=np.array([3]),
            photon_energy=np.array([9.5]),
        )
        path = str(tmp_path / "peaks.cxi")
        with CxiWriter(path, max_peaks=16) as wtr:
            wtr.append(peaks)
            assert wtr.n_events == 1
        n_back, x, y, inten, ev = read_cxi_peaks(path)
        assert n_back[0] == 2
        got = {(int(yy), int(xx)) for yy, xx in zip(y[0][:2], x[0][:2])}
        assert got == set(centers)
        assert ev[0] == 42
        assert np.all(inten[0][:2] > 0.9)

    def test_append_batches(self, tmp_path):
        path = str(tmp_path / "multi.cxi")
        z = jnp.asarray(
            np.stack([_logits_with_peaks(32, 32, [(8, 8)]),
                      _logits_with_peaks(32, 32, [(4, 4), (20, 20)])])
        )
        yx, score, n = find_peaks(z, max_peaks=8)
        with CxiWriter(path, max_peaks=8) as wtr:
            wtr.append(unpad_peaks(yx, score, n))
            wtr.append(unpad_peaks(yx, score, n))
            assert wtr.n_events == 4
        n_back, *_ = read_cxi_peaks(path)
        assert list(n_back) == [1, 2, 1, 2]
