"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

The reference has no tests at all (SURVEY.md §4); this suite follows the
strategy SURVEY.md prescribes — in-process queue/infeed unit tests plus
multi-device tests on a CPU-simulated mesh."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The axon TPU plugin ignores JAX_PLATFORMS; the config knob is honored.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _fresh_registry():
    from psana_ray_tpu.obs.registry import MetricsRegistry
    from psana_ray_tpu.transport.registry import Registry

    Registry.reset_default()
    MetricsRegistry.reset_default()
    yield
    Registry.reset_default()
    MetricsRegistry.reset_default()


@pytest.fixture
def rng():
    return np.random.default_rng(0)
