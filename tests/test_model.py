"""Tier-1: the bounded protocol model checker (ISSUE 18).

Three claims, each pinned:

1. the live rules hold — every model exhausts its bounded configuration
   (full profile: 3 frames, 2 crash injections at every transition)
   with ZERO counterexamples, inside the budget;
2. the checker would have caught the bugs — flipping one rule per model
   (drop the resend tail, requeue at the tail, commit the cursor, ack
   at ship time, skip the self-fence, skip the generation check) makes
   the matching invariant fire with a short (<= 20 step) printed
   counterexample trace;
3. the models cannot rot silently — the drift gate pins model legal
   sets against the dialogue reconstruction of the live tree in both
   directions (op removed from a model / op added to the transport /
   mode legal-set drift / ghost status), and the worker-adoption plane
   rides every protocol scan (PROTOCOL_COMPANIONS).
"""

import os
import subprocess
import sys

import pytest

from psana_ray_tpu.lint.core import (
    PROTOCOL_COMPANIONS,
    REPO_ROOT,
    ProjectIndex,
)
from psana_ray_tpu.lint.flow.protocol import extract_dialogue
from psana_ray_tpu.lint.model import all_models, explore, run_models
from psana_ray_tpu.lint.model.chain import ReplicationChainModel
from psana_ray_tpu.lint.model.checker import (
    ProtocolModelChecker,
    run_model_report,
)
from psana_ray_tpu.lint.model.core import render_trace
from psana_ray_tpu.lint.model.drift import NON_MODELED, check_drift
from psana_ray_tpu.lint.model.durable import DurableFloorModel
from psana_ray_tpu.lint.model.fencing import GroupFencingModel
from psana_ray_tpu.lint.model.stream import StreamModel
from psana_ray_tpu.lint.model.windowed import WindowedPutModel


@pytest.fixture(scope="module")
def dialogue():
    index = ProjectIndex(
        [os.path.join(REPO_ROOT, rel) for rel in PROTOCOL_COMPANIONS]
    )
    d = extract_dialogue(index)
    assert d is not None, "protocol companions no longer arm the dialogue"
    return d


# ---------------------------------------------------------------------------
# 1. the live rules hold
# ---------------------------------------------------------------------------

def test_full_profile_exhausts_every_model_with_zero_counterexamples():
    results = run_models("full")
    assert len(results) == 5
    for r in results:
        assert r.violation is None, render_trace(r)
        assert r.exhausted, (
            f"model {r.model.name} truncated by {r.truncated_by} — a "
            f"truncated run proves nothing"
        )
        assert r.states > 50  # a trivial state space would prove nothing
    # the budget claim: the whole fleet exhausts in seconds, not minutes
    assert sum(r.duration_s for r in results) < 10.0


def test_quick_profile_exhausts_too():
    # the registry entry runs this profile inside the lint budget
    for r in run_models("quick"):
        assert r.violation is None and r.exhausted
        assert r.duration_s < 1.0


# ---------------------------------------------------------------------------
# 2. seeded mutations: every flipped rule fires its invariant
# ---------------------------------------------------------------------------

MUTATIONS = [
    # (label, mutated model, invariant that must fire)
    ("windowed-resend-tail-dropped",
     lambda: WindowedPutModel(resend_full_tail=False), "holes-never"),
    ("stream-requeue-at-tail",
     lambda: StreamModel(requeue_at_head=False), "eos-never-overtakes"),
    ("stream-window-unenforced",
     lambda: StreamModel(enforce_window=False),
     "credit-window-conservation"),
    ("stream-crash-drops-unacked",
     lambda: StreamModel(requeue_lost=False), "loss-never"),
    ("durable-commit-cursor-not-processed",
     lambda: DurableFloorModel(commit_processed_only=False),
     "committed-implies-processed"),
    ("chain-ack-at-ship-time",
     lambda: ReplicationChainModel(ack_after_logged=False),
     "ack-floor<=follower-tail"),
    ("chain-no-self-fence-behind-replica",
     lambda: ReplicationChainModel(self_fence_behind=False),
     "owner-behind-replica-self-fences"),
    ("fencing-generation-check-skipped",
     lambda: GroupFencingModel(check_generation=False),
     "stale-commit-always-fenced"),
]


@pytest.mark.parametrize(
    "label,factory,invariant", MUTATIONS, ids=[m[0] for m in MUTATIONS]
)
def test_seeded_mutation_fires_with_short_counterexample(
    label, factory, invariant
):
    result = explore(factory(), profile="full")
    assert result.violation == invariant, (
        f"{label}: expected {invariant!r}, got {result.violation!r}"
    )
    assert 0 < len(result.trace) <= 20, (
        f"{label}: counterexample must be minimal-ish, got "
        f"{len(result.trace)} steps"
    )
    rendered = render_trace(result)
    print(rendered)  # the acceptance criterion: a PRINTED opcode timeline
    assert "counterexample" in rendered and invariant in rendered
    # every step is numbered and non-empty (an opcode timeline, not a
    # state dump)
    steps = rendered.splitlines()[1:-1]
    assert len(steps) == len(result.trace)


# ---------------------------------------------------------------------------
# 3. drift gate
# ---------------------------------------------------------------------------

def test_live_tree_has_no_drift_and_models_cover_the_surface(dialogue):
    drift = list(check_drift(dialogue, all_models(), full=True))
    assert not drift, "\n".join(m for m, _h in drift)


def test_removing_an_op_from_a_model_is_a_finding(dialogue):
    models = all_models()
    victim = next(m for m in models if m.name == "windowed")
    victim.WIRE_OPS = frozenset()  # instance shadow: 'W' loses its model
    drift = list(check_drift(dialogue, models, full=True))
    assert any("_OP_PUT_SEQ" in m for m, _h in drift)


def test_unmodeled_wire_op_is_a_finding(dialogue):
    d = dict(dialogue)
    d["ops"] = dict(dialogue["ops"])
    d["ops"]["_OP_FROB"] = {"handler": "_op_frob", "handler_missing": False,
                            "emits": set()}
    drift = list(check_drift(d, all_models(), full=True))
    assert any("_OP_FROB" in m and "no protocol model" in m
               for m, _h in drift)


def test_mode_legal_set_drift_is_a_finding(dialogue):
    models = all_models()
    victim = next(m for m in models if m.name == "stream")
    victim.MODE_LEGAL_OPS = frozenset({"_OP_STREAM_ACK", "_OP_BYE"})
    drift = list(check_drift(dialogue, models, full=True))
    assert any("legal-op drift" in m for m, _h in drift)


def test_ghost_status_is_a_finding(dialogue):
    models = all_models()
    victim = next(m for m in models if m.name == "durable")
    victim.WIRE_STATUSES = victim.WIRE_STATUSES | {"_ST_BOGUS"}
    drift = list(check_drift(dialogue, models, full=True))
    assert any("_ST_BOGUS" in m for m, _h in drift)


def test_non_modeled_justifications_do_not_overlap_models():
    modeled = set()
    for m in all_models():
        modeled |= m.WIRE_OPS
    assert not modeled & set(NON_MODELED)
    for op, why in NON_MODELED.items():
        assert why.strip(), f"{op} needs a written justification"


def test_registry_checker_reports_mutated_fleet(monkeypatch):
    import psana_ray_tpu.lint.model.checker as checker_mod

    def mutated_fleet():
        fleet = all_models()
        return [StreamModel(requeue_at_head=False) if m.name == "stream"
                else m for m in fleet]

    monkeypatch.setattr(checker_mod, "all_models", mutated_fleet)
    index = ProjectIndex(
        [os.path.join(REPO_ROOT, rel) for rel in PROTOCOL_COMPANIONS]
    )
    findings = list(ProtocolModelChecker().run(index))
    assert any("eos-never-overtakes" in f.message
               and "counterexample" in f.message for f in findings)


# ---------------------------------------------------------------------------
# worker-adoption plane rides the protocol scans (ISSUE 18 satellite)
# ---------------------------------------------------------------------------

def test_workers_is_a_protocol_companion(dialogue):
    assert "psana_ray_tpu/transport/workers.py" in PROTOCOL_COMPANIONS
    # the adoption handshake replays ops into _on_op; every op a worker
    # must serve locally (codec/tenant hello, cluster metadata, replica
    # setup) stays a dispatched, dialogue-visible handler
    from psana_ray_tpu.transport import evloop

    assert evloop._WORKER_LOCAL_OPS  # non-empty by construction
    handlers = {rec["handler"] for rec in dialogue["ops"].values()}
    local_handlers = {
        evloop._OPS[op] for op in evloop._WORKER_LOCAL_OPS
    }
    assert local_handlers <= handlers
    # the 'M' stream-adoption state must stay in the stream mode legal
    # set the models pin
    assert "_OP_STREAM" in dialogue["modes"]["stream"]["server_allowed"]


# ---------------------------------------------------------------------------
# CLI + report plumbing
# ---------------------------------------------------------------------------

def test_run_model_report_live_tree():
    results, drift = run_model_report(profile="full")
    assert not drift
    assert all(r.violation is None and r.exhausted for r in results)


def test_model_cli_exits_clean():
    proc = subprocess.run(
        [sys.executable, "-m", "psana_ray_tpu.lint", "--model"],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ok, exhausted" in proc.stdout
    assert "model: clean" in proc.stdout


def test_model_cli_flag_conflicts_are_usage_errors():
    proc = subprocess.run(
        [sys.executable, "-m", "psana_ray_tpu.lint", "--model",
         "--changed", "HEAD"],
        capture_output=True, text=True, timeout=60, cwd=REPO_ROOT,
    )
    assert proc.returncode == 2
