"""The example scripts run end to end (subprocess, CPU mesh) — they are
the executable documentation of the streaming APIs, so they must not rot."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_train_peaknet_example_runs():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "examples", "train_peaknet.py"),
            "--steps", "2", "--num_events", "6", "--detector", "smoke_a",
        ],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "trained 2 steps" in out.stdout, out.stdout[-2000:]
    assert "mesh={'data': 2" in out.stdout, out.stdout[-500:]
