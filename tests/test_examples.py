"""The example scripts run end to end (subprocess, CPU mesh) — they are
the executable documentation of the streaming APIs, so they must not rot."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_train_peaknet_example_runs():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "examples", "train_peaknet.py"),
            "--steps", "2", "--num_events", "6", "--detector", "smoke_a",
        ],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "trained 2 steps" in out.stdout, out.stdout[-2000:]
    assert "mesh={'data': 2" in out.stdout, out.stdout[-500:]


def test_train_peaknet_export_serving(tmp_path):
    """The train→serve continuity story end to end: --export-serving
    trains with norm='batch', folds the running stats into the
    FrozenAffine serving form (models/fold.py), and the exported
    checkpoint drives both the flax norm='frozen' model and the fused
    inference path."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    export_dir = str(tmp_path / "serving")
    out = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "examples", "train_peaknet.py"),
            "--steps", "2", "--num_events", "6", "--detector", "smoke_a",
            "--export-serving", export_dir,
        ],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "serving params" in out.stdout, out.stdout[-2000:]

    import jax.numpy as jnp
    import numpy as np

    from psana_ray_tpu.checkpoint import load_params
    from psana_ray_tpu.models import PeakNetUNetTPU

    params = load_params(export_dir)
    model = PeakNetUNetTPU(features=(16, 32), norm="frozen")
    logits = model.apply(params, jnp.ones((1, 16, 16, 1)))
    assert logits.shape == (1, 16, 16, 1)
    assert np.isfinite(np.asarray(logits, np.float32)).all()


def test_cli_runbook_tcp_end_to_end():
    """The README cluster runbook, executed: queue server CLI + producer
    CLI + consumer CLI as real subprocesses over tcp:// — the closest the
    suite gets to the reference's 5-step bring-up (`ray start --head`,
    mpirun producers, python consumers, `ray stop`)."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    server = subprocess.Popen(
        [sys.executable, "-m", "psana_ray_tpu.queue_server",
         "--host", "127.0.0.1", "--port", str(port), "--queue_size", "32"],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        producer = subprocess.run(
            [sys.executable, "-m", "psana_ray_tpu.producer",
             "--exp", "synthetic", "--num_events", "24",
             "--detector_name", "smoke_a",
             "--address", f"tcp://127.0.0.1:{port}",
             "--queue_name", "q1", "--num_consumers", "1"],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=180,
        )
        assert producer.returncode == 0, producer.stderr[-2000:]
        consumer = subprocess.run(
            [sys.executable, "-m", "psana_ray_tpu.consumer", "0",
             "--address", f"tcp://127.0.0.1:{port}",
             "--queue_name", "q1", "--quiet"],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=180,
        )
        assert consumer.returncode == 0, consumer.stderr[-2000:]
        out = consumer.stdout + consumer.stderr
        # exact phrase: a bare "24" would match log timestamps
        assert "end of stream after 24 frames" in out, out[-1500:]
    finally:
        server.terminate()
        try:
            server.wait(timeout=15)
        except subprocess.TimeoutExpired:
            server.kill()


def test_fanin_consumer_example_runs():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "fanin_consumer.py")],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "done:" in out.stdout, out.stdout[-1500:]
    assert "epix10k2M" in out.stdout and "jungfrau4M" in out.stdout
