"""Tier-1 driver for the project-invariant static analysis (ISSUE 3).

Three layers, all fast and jax-free:

1. the shipped tree is CLEAN under the full registry (including
   allowlist rot — a stale excuse is a failure), inside the 5 s budget;
2. every registered checker has a known-bad fixture that MUST flag and
   a known-good fixture that MUST pass (``tests/lint_fixtures/``), so a
   checker that silently stops firing — or starts false-positiving on
   the sanctioned pattern — is itself a tier-1 failure;
3. the CLI contract CI scripts rely on: exit 0 clean, exit 1 with
   ``file:line`` findings when a bad snippet is in scope, ``--json``
   counts including zeros.
"""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys

import pytest

from psana_ray_tpu.lint import ALLOWLIST, Allow, REGISTRY, run_lint

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = pathlib.Path(__file__).resolve().parent / "lint_fixtures"

# checker name -> fixture stem (registry names are kebab-case)
_STEM = {name: name.replace("-", "_") for name in REGISTRY}


# ---------------------------------------------------------------------------
# 1. the shipped tree is clean, fast
# ---------------------------------------------------------------------------

def test_shipped_tree_is_clean_under_full_registry():
    result = run_lint()
    assert result.ok, "lint findings on the shipped tree:\n" + "\n".join(
        f.render() for f in result.findings
    )
    assert result.files_scanned > 50  # the whole package + bench.py
    assert set(result.checkers_run) == set(REGISTRY)
    assert result.duration_s < 25.0, (
        f"full registry took {result.duration_s:.2f}s — the budget keeps "
        f"lint viable as a pre-commit/tier-1 gate (15 s through ISSUE 9; "
        f"ISSUE 10's flow layer — CFGs with exception edges, the resolved "
        f"call graph, three whole-program analyses — measures 8.5-10 s "
        f"idle on this CPU-share-throttled box, so 25 s keeps the same "
        f"~1.6x loaded-box headroom the old budget carried. Scale it "
        f"with the tree, never delete it; the <2 s incremental gate is "
        f"--changed, pinned below)"
    )


def test_every_allowlist_entry_has_a_justification():
    for entry in ALLOWLIST:
        assert entry.why.strip(), entry
    with pytest.raises(ValueError, match="justification"):
        Allow("hot-alloc", "x.py", "bytes(", why="  ")


# ---------------------------------------------------------------------------
# 2. fixture pairs: each checker must flag its bad snippet, pass its good one
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("checker", sorted(REGISTRY))
def test_checker_flags_its_bad_fixture(checker):
    path = FIXTURES / f"{_STEM[checker]}_bad.py"
    assert path.exists(), f"every checker needs a bad fixture: {path}"
    result = run_lint(paths=[path], checkers=[checker], use_allowlist=False)
    mine = [f for f in result.findings if f.checker == checker]
    assert mine, f"{checker} failed to flag its known-bad fixture {path.name}"
    for f in mine:
        assert f.line > 0 and f.path.endswith(path.name) and f.hint


@pytest.mark.parametrize("checker", sorted(REGISTRY))
def test_checker_passes_its_good_fixture(checker):
    path = FIXTURES / f"{_STEM[checker]}_good.py"
    assert path.exists(), f"every checker needs a good fixture: {path}"
    result = run_lint(paths=[path], checkers=[checker], use_allowlist=False)
    mine = [f for f in result.findings if f.checker == checker]
    assert not mine, (
        f"{checker} false-positives on its sanctioned-pattern fixture:\n"
        + "\n".join(f.render() for f in mine)
    )


def test_bad_fixtures_do_not_crash_other_checkers():
    # the full registry must RUN over hostile snippets (a checker that
    # throws on unexpected shapes would mask real findings elsewhere)
    paths = sorted(FIXTURES.glob("*_bad.py"))
    result = run_lint(paths=paths, use_allowlist=False)
    assert len(result.findings) >= len(paths)


# ---------------------------------------------------------------------------
# 3. allowlist rot: an entry that suppresses nothing fails the run
# ---------------------------------------------------------------------------

def test_stale_allowlist_entry_is_a_finding():
    stale = Allow(
        "hot-alloc", "transport/tcp.py", "this line does not exist anywhere",
        why="fixture: deliberately stale",
    )
    result = run_lint(allowlist=(*ALLOWLIST, stale))
    rot = [f for f in result.findings if f.checker == "allowlist-rot"]
    assert len(rot) == 1 and "this line does not exist" in rot[0].message
    # ... and ONLY the stale entry rots: the live ones all still match
    assert [f for f in result.findings if f.checker != "allowlist-rot"] == []


def test_live_allowlist_suppresses_without_rot():
    result = run_lint()  # the real allowlist, the real tree
    assert not [f for f in result.findings if f.checker == "allowlist-rot"]


# ---------------------------------------------------------------------------
# 4. CLI contract (the CI gate): exit codes, file:line findings, --json
# ---------------------------------------------------------------------------

def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "psana_ray_tpu.lint", *args],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=60,
    )


def test_cli_exits_zero_and_emits_json_on_clean_tree():
    proc = _cli("--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["clean"] is True and payload["findings"] == []
    # zeros present for every checker: "ran clean" != "did not run"
    assert set(payload["counts_by_checker"]) == set(REGISTRY)
    assert all(v == 0 for v in payload["counts_by_checker"].values())


def test_cli_exits_nonzero_with_findings_on_bad_snippet():
    bad = FIXTURES / "wire_protocol_bad.py"
    proc = _cli("--no-allowlist", str(bad))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "wire_protocol_bad.py:" in proc.stdout  # file:line rendering
    assert "[wire-protocol]" in proc.stdout


def test_cli_unknown_checker_is_a_usage_error():
    assert _cli("--checker", "no-such-checker").returncode == 2


def test_cli_missing_path_is_a_usage_error_not_findings():
    # CI reads exit 1 as "findings present": a typo'd path must exit 2
    proc = _cli("no/such/file.py")
    assert proc.returncode == 2 and "no such file" in proc.stderr


def test_blocking_roots_rot_is_a_finding():
    """A scan that INCLUDES a root's home file where the root no longer
    resolves (the rename-inside-the-file rot class) must say so, not
    silently degrade to a no-op — while an incremental scan that merely
    EXCLUDES the home file (a --changed diff not touching serving/ or
    infeed/, the ISSUE 15 false-fire) must stay quiet."""
    import shutil

    rot_dir = FIXTURES / "_tmp_rot_home" / "infeed"
    rot_dir.mkdir(parents=True, exist_ok=True)
    rot_file = rot_dir / "batcher.py"
    rot_file.write_text("def something_else():\n    pass\n")
    try:
        result = run_lint(paths=[rot_file], checkers=["blocking-hot-path"])
        assert any(
            "resolves to no function" in f.message for f in result.findings
        ), result.findings
    finally:
        shutil.rmtree(FIXTURES / "_tmp_rot_home")
    # the non-firing half: a >10-file scan WITHOUT any home file is an
    # incremental diff, not rot
    no_roots = sorted((REPO_ROOT / "psana_ray_tpu" / "lint").rglob("*.py"))
    assert len(no_roots) > 10
    result = run_lint(paths=no_roots, checkers=["blocking-hot-path"])
    assert not any(
        "resolves to no function" in f.message for f in result.findings
    ), result.findings


def test_splice_pump_and_supervisor_are_audited_roots():
    """ISSUE 17: the kernel pass-through pump and the worker supervisor
    loop are event-loop-blocking roots of their own. The dedicated
    fixture pair proves both directions WITHOUT an EventLoop.run in
    scope — if either root rots out of ROOTS, the bad fixture stops
    flagging and this test fails."""
    bad = FIXTURES / "splice_pump_bad.py"
    result = run_lint(
        paths=[bad], checkers=["event-loop-blocking"], use_allowlist=False
    )
    mine = [f for f in result.findings if f.checker == "event-loop-blocking"]
    assert mine, "splice pump / supervisor blocking idioms did not flag"
    # both roots must contribute findings, not just one
    msgs = "\n".join(f.message for f in mine)
    assert "_pump_span" in msgs, msgs
    assert "_supervise" in msgs or "WorkerSupervisor" in msgs, msgs
    good = FIXTURES / "splice_pump_good.py"
    result = run_lint(
        paths=[good], checkers=["event-loop-blocking"], use_allowlist=False
    )
    mine = [f for f in result.findings if f.checker == "event-loop-blocking"]
    assert not mine, "\n".join(f.render() for f in mine)


def test_unattached_guarded_by_annotation_is_a_finding():
    import textwrap

    bad = FIXTURES.parent / "lint_fixtures"  # reuse the dir for a temp file
    path = bad / "_tmp_unattached_guard.py"
    path.write_text(textwrap.dedent("""
        class C:
            def __init__(self):
                # guarded-by: _lock
                pass
    """))
    try:
        result = run_lint(paths=[path], checkers=["lock-discipline"])
        assert any("attached to no attribute" in f.message for f in result.findings)
    finally:
        path.unlink()


def test_hot_alloc_covers_the_span_hot_path_fixtures():
    """ISSUE 4 satellite: the tracing span path is hot-path territory —
    the opt-in marker pair pins that hot-alloc keeps flagging per-frame
    allocation idioms there and passes the sanctioned struct-pack /
    counter-gate / buffered-spool patterns."""
    bad = FIXTURES / "span_hot_path_bad.py"
    good = FIXTURES / "span_hot_path_good.py"
    flagged = run_lint(paths=[bad], checkers=["hot-alloc"], use_allowlist=False)
    tags = {f.message.split("]")[0].lstrip("[") for f in flagged.findings}
    assert {"to_bytes-call", "raw-recv", "bytes-materialize", "tobytes"} <= tags, (
        flagged.findings
    )
    clean = run_lint(paths=[good], checkers=["hot-alloc"], use_allowlist=False)
    assert not clean.findings, clean.findings


def test_tracing_module_is_under_the_hot_alloc_screen():
    # obs/tracing.py opts in via the exact marker line — the span emit
    # path stays covered without editing the checker's built-in list
    tracing = REPO_ROOT / "psana_ray_tpu" / "obs" / "tracing.py"
    head = tracing.read_text().splitlines()[:5]
    assert any(ln.strip() == "# lint: hot-path" for ln in head)
    result = run_lint(paths=[tracing], checkers=["hot-alloc"], use_allowlist=False)
    assert not result.findings, result.findings


def test_hot_alloc_covers_the_codec_hot_path_fixtures():
    """ISSUE 9 satellite: the wire-compression codec is hot-path
    territory — the fixture pair pins that hot-alloc keeps flagging
    per-frame allocation idioms inside compress/decompress code and
    passes the sanctioned lease-staging / .data.cast("B") /
    recv_into patterns."""
    bad = FIXTURES / "codec_hot_path_bad.py"
    good = FIXTURES / "codec_hot_path_good.py"
    flagged = run_lint(paths=[bad], checkers=["hot-alloc"], use_allowlist=False)
    tags = {f.message.split("]")[0].lstrip("[") for f in flagged.findings}
    assert {"to_bytes-call", "tobytes", "raw-recv", "bytes-materialize"} <= tags, (
        flagged.findings
    )
    clean = run_lint(paths=[good], checkers=["hot-alloc"], use_allowlist=False)
    assert not clean.findings, clean.findings


def test_wire_protocol_checker_verifies_codec_opcode_both_ways():
    """ISSUE 9 satellite: the codec-negotiation opcode ('Z') must stay
    wired on both sides — client sender in tcp.py, server dispatch-
    table entry in evloop.py — or tier-1 fails before any peer sees a
    runtime protocol error."""
    import ast

    tcp = REPO_ROOT / "psana_ray_tpu" / "transport" / "tcp.py"
    evloop = REPO_ROOT / "psana_ray_tpu" / "transport" / "evloop.py"
    tree = ast.parse(tcp.read_text())
    assert any(
        isinstance(n, ast.Assign)
        and isinstance(n.targets[0], ast.Name)
        and n.targets[0].id == "_OP_CODEC"
        for n in tree.body
    ), "_OP_CODEC opcode constant missing from tcp.py"
    repl = REPO_ROOT / "psana_ray_tpu" / "cluster" / "replication.py"
    result = run_lint(paths=[tcp, evloop, repl], checkers=["wire-protocol"])
    assert not result.findings, result.findings


def test_blocking_checker_reaches_the_codec_decode_path():
    """ISSUE 9 satellite: the compressed-payload decode runs inside the
    stream reader's drain (TcpStreamReader -> _recv_payload ->
    decode_payload -> codec decompress), so a sleep smuggled into a
    decompressor must flag through the same name-based graph — and the
    REAL codec module must scan clean from that graph."""
    import textwrap

    path = FIXTURES / "_tmp_codec_decode_sleep.py"
    path.write_text(textwrap.dedent("""
        import time


        def batches_from_queue(queue, batch_size):
            pop = getattr(queue, "get_batch_stream", None) or queue.get_batch
            while True:
                items = pop(batch_size, timeout=0.01)
                if not items:
                    return
                yield items


        class StreamReader:
            def get_batch_stream(self, max_items, timeout=None):
                return [decode_payload(b) for b in self._bufs]


        def decode_payload(buf):
            return _decode_compressed(buf)


        def _decode_compressed(buf):
            return SlowCodec().decompress(buf, bytearray(64))


        class SlowCodec:
            def decompress(self, src, dst):
                time.sleep(0.001)  # must flag: stall inside the drain
                return None
    """))
    try:
        result = run_lint(paths=[path], checkers=["blocking-hot-path"])
        hits = [
            f
            for f in result.findings
            if "time.sleep" in f.message and "decompress" in f.message
        ]
        assert hits, result.findings
    finally:
        path.unlink()
    # ...and the REAL decode path (batcher -> tcp stream reader ->
    # codec) is inside the audited set with no findings
    tcp = REPO_ROOT / "psana_ray_tpu" / "transport" / "tcp.py"
    codec = REPO_ROOT / "psana_ray_tpu" / "transport" / "codec.py"
    batcher = REPO_ROOT / "psana_ray_tpu" / "infeed" / "batcher.py"
    real = run_lint(paths=[tcp, codec, batcher], checkers=["blocking-hot-path"])
    assert not real.findings, real.findings


def test_wire_protocol_checker_verifies_anchor_opcode_both_ways():
    """The clock-anchor opcode ('A', ISSUE 4) must stay wired on both
    sides: deleting either the client sender (tcp.py) or the server
    dispatch-table entry (evloop.py — the only server since ISSUE 7
    removed the threaded mode) becomes a tier-1 failure, not a runtime
    protocol error."""
    import ast

    tcp = REPO_ROOT / "psana_ray_tpu" / "transport" / "tcp.py"
    evloop = REPO_ROOT / "psana_ray_tpu" / "transport" / "evloop.py"
    repl = REPO_ROOT / "psana_ray_tpu" / "cluster" / "replication.py"
    tree = ast.parse(tcp.read_text())
    assert any(
        isinstance(n, ast.Assign)
        and isinstance(n.targets[0], ast.Name)
        and n.targets[0].id == "_OP_ANCHOR"
        for n in tree.body
    ), "_OP_ANCHOR opcode constant missing from tcp.py"
    # the generic checker sees it both ways across the protocol set
    # (replication.py carries the 'H'/'V' senders since ISSUE 11)
    result = run_lint(paths=[tcp, evloop, repl], checkers=["wire-protocol"])
    assert not result.findings, result.findings


def test_wire_protocol_checker_flags_sent_but_never_dispatched():
    """ISSUE 5 satellite: a new opcode wired into the sender but never
    dispatched must be a lint finding (the runtime symptom is the peer
    answering protocol-error and dropping the connection on first use)."""
    bad = FIXTURES / "wire_protocol_bad.py"
    result = run_lint(paths=[bad], checkers=["wire-protocol"], use_allowlist=False)
    flush = [f for f in result.findings if "_OP_FLUSH" in f.message]
    assert len(flush) == 1, result.findings
    assert "never matched" in flush[0].message  # sent, no dispatch arm


def test_wire_protocol_checker_verifies_streaming_opcodes_both_ways():
    """The streaming/windowed opcodes (ISSUE 5: 'M' subscribe, 'K'
    cumulative ack, 'W' windowed put, 'U' bounded-wait put, 'D'
    bounded-wait get-batch) must stay wired on both sides — deleting a
    sender or a dispatch arm becomes a tier-1 failure, not a runtime
    protocol error."""
    import ast

    tcp = REPO_ROOT / "psana_ray_tpu" / "transport" / "tcp.py"
    tree = ast.parse(tcp.read_text())
    defined = {
        n.targets[0].id
        for n in tree.body
        if isinstance(n, ast.Assign) and isinstance(n.targets[0], ast.Name)
    }
    for op in (
        "_OP_STREAM",
        "_OP_STREAM_ACK",
        "_OP_PUT_SEQ",
        "_OP_PUT_WAIT",
        "_OP_GET_BATCH_WAIT",
    ):
        assert op in defined, f"{op} opcode constant missing from tcp.py"
    # the generic checker sees every one both ways across the protocol
    # set (dispatch moved to evloop.py's _OPS table with ISSUE 7; the
    # replication senders live in cluster/replication.py since ISSUE 11)
    evloop = REPO_ROOT / "psana_ray_tpu" / "transport" / "evloop.py"
    repl = REPO_ROOT / "psana_ray_tpu" / "cluster" / "replication.py"
    result = run_lint(paths=[tcp, evloop, repl], checkers=["wire-protocol"])
    assert not result.findings, result.findings


def test_wire_protocol_checker_verifies_cluster_opcode_both_ways():
    """ISSUE 7 satellite: the cluster/group RPC opcode ('N') must stay
    wired on both sides — sender in the client (tcp.py cluster_rpc),
    dispatch in the event loop's _OPS table. The checker resolves uses
    ACROSS the scanned files and understands dict-literal dispatch keys
    (``_OP_CLUSTER[0]: "_op_cluster"``); scanning the protocol file
    alone must conversely report the missing dispatch, so deleting the
    evloop arm cannot pass silently."""
    import ast

    tcp = REPO_ROOT / "psana_ray_tpu" / "transport" / "tcp.py"
    evloop = REPO_ROOT / "psana_ray_tpu" / "transport" / "evloop.py"
    repl = REPO_ROOT / "psana_ray_tpu" / "cluster" / "replication.py"
    tree = ast.parse(tcp.read_text())
    assert any(
        isinstance(n, ast.Assign)
        and isinstance(n.targets[0], ast.Name)
        and n.targets[0].id == "_OP_CLUSTER"
        for n in tree.body
    ), "_OP_CLUSTER opcode constant missing from tcp.py"
    result = run_lint(paths=[tcp, evloop, repl], checkers=["wire-protocol"])
    assert not result.findings, result.findings
    # cross-file is load-bearing: without the dispatch table in scope,
    # every sent opcode (including 'N') must flag as never-matched
    alone = run_lint(paths=[tcp], checkers=["wire-protocol"], use_allowlist=False)
    assert any(
        "_OP_CLUSTER" in f.message and "never matched" in f.message
        for f in alone.findings
    ), alone.findings


def test_wire_protocol_checker_verifies_replication_opcodes_both_ways():
    """ISSUE 11 satellite: the replication opcodes ('H' replica-
    subscribe, 'V' replica-append, 'Y' promote) must stay wired on both
    sides. The senders live in cluster/replication.py (the owner's
    shipping link) and tcp.py (the failover promote), the dispatch in
    evloop.py — which is exactly why replication.py is a PROTOCOL
    companion: a scan without it must flag the phantom asymmetry rather
    than pass silently."""
    import ast

    from psana_ray_tpu.lint.core import PROTOCOL_COMPANIONS

    tcp = REPO_ROOT / "psana_ray_tpu" / "transport" / "tcp.py"
    evloop = REPO_ROOT / "psana_ray_tpu" / "transport" / "evloop.py"
    repl = REPO_ROOT / "psana_ray_tpu" / "cluster" / "replication.py"
    tree = ast.parse(tcp.read_text())
    defined = {
        n.targets[0].id
        for n in tree.body
        if isinstance(n, ast.Assign) and isinstance(n.targets[0], ast.Name)
    }
    for op in ("_OP_REPL_OPEN", "_OP_REPL_APPEND", "_OP_PROMOTE"):
        assert op in defined, f"{op} opcode constant missing from tcp.py"
    result = run_lint(paths=[tcp, evloop, repl], checkers=["wire-protocol"])
    assert not result.findings, result.findings
    # the cross-file senders are load-bearing: without replication.py
    # in scope the replica opcodes look like dead dispatch surface —
    # the reason it rides PROTOCOL_COMPANIONS into every --changed run
    assert "psana_ray_tpu/cluster/replication.py" in PROTOCOL_COMPANIONS
    without = run_lint(
        paths=[tcp, evloop], checkers=["wire-protocol"], use_allowlist=False
    )
    asym = {
        f.message.split()[1]
        for f in without.findings
        if "no code ever sends it" in f.message
    }
    assert {"_OP_REPL_OPEN", "_OP_REPL_APPEND"} <= asym, without.findings


def test_replication_wire_fixture_pair():
    """The seeded replication half-protocol flags both failure shapes
    (append sent with no dispatch arm; promote dispatched with no
    sender) and the complete triple passes."""
    bad = FIXTURES / "replication_wire_bad.py"
    result = run_lint(paths=[bad], checkers=["wire-protocol"], use_allowlist=False)
    msgs = [f.message for f in result.findings]
    assert any(
        "_OP_RAPP" in m and "never matched" in m for m in msgs
    ), msgs
    assert any(
        "_OP_RPROMOTE" in m and "no code ever sends it" in m for m in msgs
    ), msgs
    good = FIXTURES / "replication_wire_good.py"
    result = run_lint(paths=[good], checkers=["wire-protocol"], use_allowlist=False)
    assert not result.findings, result.findings


def test_segment_lifecycle_covers_the_follower_truncate_path():
    """ISSUE 11 satellite: the replica reconciliation surface —
    SegmentLog.truncate_to / reset_to pop, close and re-mint segments —
    must stay clean under the segment-lifecycle checker (a leaked
    mapping per truncate would pin an mmap per owner reconnect)."""
    log = REPO_ROOT / "psana_ray_tpu" / "storage" / "log.py"
    seg = REPO_ROOT / "psana_ray_tpu" / "storage" / "segment.py"
    repl = REPO_ROOT / "psana_ray_tpu" / "cluster" / "replication.py"
    result = run_lint(
        paths=[log, seg, repl], checkers=["segment-lifecycle"]
    )
    assert not result.findings, result.findings
    # ...and the checker is not inert on this population: a seeded
    # truncate that drops the popped segment must flag
    import textwrap

    snippet = FIXTURES / "_repl_truncate_leak.py"
    snippet.write_text(textwrap.dedent("""
        class Log:
            def truncate_to(self, offset):
                seg = self._new_segment(offset)
                self.tail = offset
    """))
    try:
        result = run_lint(
            paths=[snippet], checkers=["segment-lifecycle"],
            use_allowlist=False,
        )
        assert result.findings, "seeded truncate leak did not flag"
    finally:
        snippet.unlink()


def test_blocking_checker_covers_the_stream_reader_path():
    """ISSUE 5 satellite: the server-push stream drain the batcher
    prefers (getattr get_batch_stream indirection) must be inside the
    blocking-hot-path call graph — a sleep smuggled into a stream reader
    has to flag even though the getattr hides the edge."""
    import textwrap

    path = FIXTURES / "_tmp_stream_reader_sleep.py"
    path.write_text(textwrap.dedent("""
        import time


        def batches_from_queue(queue, batch_size):
            pop = getattr(queue, "get_batch_stream", None) or queue.get_batch
            while True:
                items = pop(batch_size, timeout=0.01)
                if not items:
                    return
                yield items


        class StreamReader:
            def get_batch_stream(self, max_items, timeout=None):
                time.sleep(0.001)  # must flag: stall in the drain loop
                return []
    """))
    try:
        result = run_lint(paths=[path], checkers=["blocking-hot-path"])
        hits = [
            f
            for f in result.findings
            if "time.sleep" in f.message and "get_batch_stream" in f.message
        ]
        assert hits, result.findings
    finally:
        path.unlink()


def test_real_stream_reader_is_reachable_and_clean():
    """...and the REAL TcpStreamReader is in that audited set (the
    TcpQueueClient exclusion must not swallow it) with no findings: its
    waits are caller-timeout-bounded socket reads, never sleeps."""
    tcp = REPO_ROOT / "psana_ray_tpu" / "transport" / "tcp.py"
    batcher = REPO_ROOT / "psana_ray_tpu" / "infeed" / "batcher.py"
    result = run_lint(paths=[tcp, batcher], checkers=["blocking-hot-path"])
    assert not result.findings, result.findings
    # reachability, not just absence-of-findings: the checker's seed
    # edges must name the stream drain
    from psana_ray_tpu.lint.checkers.blocking import SEED_EDGES

    assert "get_batch_stream" in SEED_EDGES["batches_from_queue"]


def test_blocking_checker_covers_the_cluster_merge_drain():
    """ISSUE 7 satellite: the cluster client's partition-merge drain is
    inside the blocking-hot-path audited graph through the same
    ``get_batch_stream`` seed edge as the single-server stream reader —
    a sleep pacing the sweep must flag (fixture pair), and the REAL
    ClusterClient must scan clean."""
    bad = FIXTURES / "cluster_merge_drain_bad.py"
    good = FIXTURES / "cluster_merge_drain_good.py"
    flagged = run_lint(paths=[bad], checkers=["blocking-hot-path"], use_allowlist=False)
    hits = [
        f for f in flagged.findings
        if "time.sleep" in f.message and "_merge_drain" in f.message
    ]
    assert hits, flagged.findings
    clean = run_lint(paths=[good], checkers=["blocking-hot-path"], use_allowlist=False)
    assert not clean.findings, clean.findings
    # ...and the shipped cluster client is in the audited set with no
    # findings (its waits are partition-client socket timeouts and one
    # interruptible Event pause, every one caller-deadline-bounded)
    cluster_dir = REPO_ROOT / "psana_ray_tpu" / "cluster"
    batcher = REPO_ROOT / "psana_ray_tpu" / "infeed" / "batcher.py"
    tcp = REPO_ROOT / "psana_ray_tpu" / "transport" / "tcp.py"
    real = run_lint(
        paths=[*sorted(cluster_dir.glob("*.py")), batcher, tcp],
        checkers=["blocking-hot-path"],
    )
    assert not real.findings, real.findings


def test_blocking_checker_covers_the_gateway_dispatch():
    """ISSUE 12 satellite: the serving gateway's dispatch loop is
    inside the blocking-hot-path audited graph — its own ROOTS entries
    plus the same ``get_batch*`` seed edges as batches_from_queue on
    serve_queue's getattr drain preference. A sleep pacing the idle
    wait must flag (fixture pair), and the REAL ServingGateway must
    scan clean (its idle pause is a bounded, offer()-woken Event
    wait)."""
    bad = FIXTURES / "gateway_dispatch_bad.py"
    good = FIXTURES / "gateway_dispatch_good.py"
    flagged = run_lint(paths=[bad], checkers=["blocking-hot-path"], use_allowlist=False)
    hits = [
        f for f in flagged.findings
        if "time.sleep" in f.message and "ServingGateway.run" in f.message
    ]
    assert hits, flagged.findings
    clean = run_lint(paths=[good], checkers=["blocking-hot-path"], use_allowlist=False)
    assert not clean.findings, clean.findings
    # ...and the shipped gateway is in the audited set with no findings
    serving_dir = REPO_ROOT / "psana_ray_tpu" / "serving"
    batcher = REPO_ROOT / "psana_ray_tpu" / "infeed" / "batcher.py"
    real = run_lint(
        paths=[*sorted(serving_dir.glob("*.py")), batcher],
        checkers=["blocking-hot-path"],
    )
    assert not real.findings, real.findings
    # reachability, not just absence-of-findings: the gateway roots and
    # serve_queue's drain seeds must be declared
    from psana_ray_tpu.lint.checkers.blocking import ROOTS, SEED_EDGES

    assert "ServingGateway.serve_queue" in ROOTS
    assert "ServingGateway.dispatch_once" in ROOTS
    assert "get_batch_stream" in SEED_EDGES["serve_queue"]


def test_blocking_checker_covers_the_autotune_actuation_path():
    """ISSUE 15 satellite: the autotune controller's actuation path —
    the controller tick and the knob-registry apply every setter runs
    under — is inside the blocking-hot-path audited graph. A sleep
    pacing a setter or the tick must flag (fixture pair), and the REAL
    autotune package must scan clean (setters are lock-guarded
    assignments or deadline-bounded client exchanges; pacing lives in
    the daemon's stoppable Event wait)."""
    bad = FIXTURES / "autotune_actuate_bad.py"
    good = FIXTURES / "autotune_actuate_good.py"
    flagged = run_lint(paths=[bad], checkers=["blocking-hot-path"], use_allowlist=False)
    hits = [
        f for f in flagged.findings
        if "time.sleep" in f.message
        and ("KnobRegistry.apply" in f.message or "HillClimber.tick" in f.message)
    ]
    assert len(hits) >= 2, flagged.findings
    clean = run_lint(paths=[good], checkers=["blocking-hot-path"], use_allowlist=False)
    assert not clean.findings, clean.findings
    # ...and the shipped controller + knob factories are in the audited
    # set with no findings
    autotune_dir = REPO_ROOT / "psana_ray_tpu" / "autotune"
    real = run_lint(
        paths=sorted(autotune_dir.glob("*.py")),
        checkers=["blocking-hot-path"],
    )
    assert not real.findings, real.findings
    from psana_ray_tpu.lint.checkers.blocking import ROOTS

    assert "HillClimber.tick" in ROOTS
    assert "KnobRegistry.apply" in ROOTS


def test_blocking_checker_covers_the_flame_sampler():
    """ISSUE 16 satellite: the continuous profiler's sampling loop —
    it fires ~97 times a second in EVERY pipeline process — is inside
    the blocking-hot-path audited graph. A ``time.sleep`` pacing the
    loop (or smuggled into the per-sample billing) must flag (fixture
    pair), and the REAL sampler must scan clean (pacing is a bounded,
    drift-corrected Event wait; shutdown join is timeout-bounded)."""
    bad = FIXTURES / "prof_sample_bad.py"
    good = FIXTURES / "prof_sample_good.py"
    flagged = run_lint(paths=[bad], checkers=["blocking-hot-path"], use_allowlist=False)
    hits = [
        f for f in flagged.findings
        if "time.sleep" in f.message and "FlameSampler" in f.message
    ]
    assert len(hits) >= 2, flagged.findings
    clean = run_lint(paths=[good], checkers=["blocking-hot-path"], use_allowlist=False)
    assert not clean.findings, clean.findings
    # ...and the shipped profiler is in the audited set with no findings
    prof_dir = REPO_ROOT / "psana_ray_tpu" / "obs" / "profiling"
    real = run_lint(
        paths=sorted(prof_dir.glob("*.py")),
        checkers=["blocking-hot-path"],
    )
    assert not real.findings, real.findings
    from psana_ray_tpu.lint.checkers.blocking import ROOTS

    assert "FlameSampler._run" in ROOTS
    assert "FlameSampler._sample_once" in ROOTS


def test_sample_path_marker_covers_the_flame_sampler():
    """ISSUE 16 satellite: the sampler's hot functions carry the
    ``# lint: sample-path`` marker, so the telemetry-discipline
    checker's allocation ban (no displays, no comprehensions, no
    f-strings, no allocating builtins) guards them — and the shipped
    package passes it."""
    sampler_py = (
        REPO_ROOT / "psana_ray_tpu" / "obs" / "profiling" / "sampler.py"
    ).read_text()
    from psana_ray_tpu.lint.checkers.telemetry import SAMPLE_MARKER

    # the trie fold, the per-tick walk, and the on-CPU probe are all hot
    assert sampler_py.count(SAMPLE_MARKER) >= 3, (
        "the sampler hot path lost its sample-path markers"
    )
    prof_dir = REPO_ROOT / "psana_ray_tpu" / "obs" / "profiling"
    real = run_lint(
        paths=sorted(prof_dir.glob("*.py")),
        checkers=["telemetry-discipline"],
    )
    assert not real.findings, real.findings


def test_telemetry_discipline_covers_the_autotune_source():
    """ISSUE 15 satellite: the ``autotune`` obs source (the knob
    registry's snapshot) is a lock-owning snapshot class — the
    telemetry-discipline checker must cover it and find it clean."""
    knobs = REPO_ROOT / "psana_ray_tpu" / "autotune" / "knobs.py"
    result = run_lint(paths=[knobs], checkers=["telemetry-discipline"])
    assert not result.findings, result.findings


def test_event_loop_checker_roots_resolve_and_real_loop_is_clean():
    """ISSUE 6 satellite: the event-loop-blocking checker must root at
    the REAL loop dispatch (EventLoop.run) and find the shipped loop
    clean — its sends go through the non-blocking write queue, its reads
    through the incremental recv_into state machine, its waits through
    the timer heap."""
    evloop = REPO_ROOT / "psana_ray_tpu" / "transport" / "evloop.py"
    tcp = REPO_ROOT / "psana_ray_tpu" / "transport" / "tcp.py"
    result = run_lint(paths=[evloop, tcp], checkers=["event-loop-blocking"])
    assert not result.findings, result.findings
    from psana_ray_tpu.lint.checkers.evblocking import ROOTS

    assert "EventLoop.run" in ROOTS


def test_event_loop_checker_flags_a_smuggled_sleep_in_loop_code():
    """A sleep (or blocking send helper) smuggled into code the loop
    dispatch reaches must flag even through attribute-call edges."""
    import textwrap

    path = FIXTURES / "_tmp_evloop_sleep.py"
    path.write_text(textwrap.dedent("""
        import time


        class EventLoop:
            def run(self):
                while True:
                    for key, mask in self._sel.select(0.1):
                        key.data.on_readable()


        class _Conn:
            def on_readable(self):
                self.queue.drain_slowly()


        class SlowQueue:
            def drain_slowly(self):
                time.sleep(0.05)  # must flag: freezes every connection
    """))
    try:
        result = run_lint(paths=[path], checkers=["event-loop-blocking"])
        hits = [
            f
            for f in result.findings
            if "time.sleep" in f.message and "drain_slowly" in f.message
        ]
        assert hits, result.findings
    finally:
        path.unlink()


def test_flow_layer_protocol_pair_scans_clean_and_reconstructs():
    """ISSUE 10 tentpole: the three flow analyses find the REAL
    transport protocol clean, and the dialogue reconstruction covers
    every opcode in the dispatch table with arms on both sides plus the
    mode tables the transport actually enforces."""
    from psana_ray_tpu.lint import ProjectIndex
    from psana_ray_tpu.lint.flow.protocol import extract_dialogue

    tcp = REPO_ROOT / "psana_ray_tpu" / "transport" / "tcp.py"
    evloop = REPO_ROOT / "psana_ray_tpu" / "transport" / "evloop.py"
    codec = REPO_ROOT / "psana_ray_tpu" / "transport" / "codec.py"
    repl = REPO_ROOT / "psana_ray_tpu" / "cluster" / "replication.py"
    result = run_lint(
        paths=[tcp, evloop, codec, repl],
        checkers=["protocol-dialogue", "lockset-inference", "resource-flow"],
    )
    assert not result.findings, result.findings

    index = ProjectIndex([tcp, evloop, repl])
    d = extract_dialogue(index)
    assert d is not None
    # every dispatched opcode has a server handler AND a client sender
    assert len(d["ops"]) >= 20  # 22 opcodes; 'K'/'V' acked in-dispatch
    for op, rec in d["ops"].items():
        assert not rec["handler_missing"], op
        assert rec["senders"], f"{op} has no client sender"
    # the streamed mode allows exactly ack + bye + the 'M' window
    # RESIZE (ISSUE 15 autotune: same header as the subscribe, applied
    # to the open stream) on both sides
    stream = d["modes"]["stream"]
    assert stream["opened_by"] == "_OP_STREAM"
    assert stream["server_allowed"] == {
        "_OP_STREAM_ACK", "_OP_BYE", "_OP_STREAM",
    }
    assert stream["client_attr"] == "_stream"
    # replay is pull-mode: stream subscribe is illegal server-side
    replay = d["modes"]["replay"]
    assert replay["opened_by"] == "_OP_REPLAY"
    assert "_OP_STREAM" in replay["illegal_ops"]
    assert replay["client_attr"] == "_replay_args"
    # replica links (ISSUE 11) carry exactly append + bye — the
    # legal-op set pinned the same way as stream/replay modes
    replica = d["modes"]["replica"]
    assert replica["opened_by"] == "_OP_REPL_OPEN"
    assert replica["server_allowed"] == {"_OP_REPL_APPEND", "_OP_BYE"}
    assert replica["client_attr"] == "_stream"


def test_protocol_dialogue_flags_seeded_desync():
    """Acceptance pin: a server reply arm with no client handler (the
    bad fixture's bare-status probe) must flag, as must the unguarded
    sender the server would kill on a streamed connection."""
    bad = FIXTURES / "protocol_dialogue_bad.py"
    result = run_lint(paths=[bad], checkers=["protocol-dialogue"], use_allowlist=False)
    msgs = [f.message for f in result.findings]
    assert any("never branches on the status byte" in m for m in msgs), msgs
    assert any("rejects on a" in m and "mode connection" in m for m in msgs), msgs


def test_resource_flow_catches_the_corrupt_head_shape():
    """The PR 9 class: an acquire whose hand-off is preceded by a
    raising call, with no except-release — exception-edge-only, which
    the syntactic lease checker cannot see (it accepts the fixture)."""
    bad = FIXTURES / "resource_flow_bad.py"
    flow = run_lint(paths=[bad], checkers=["resource-flow"], use_allowlist=False)
    assert any("exception path" in f.message for f in flow.findings), flow.findings
    assert any("fall-through path" in f.message for f in flow.findings)
    # the two classes a whole-handler-body walk / attribute-deref escape
    # would mask: a release under a guard UNRELATED to the lease, and a
    # local alias of the view
    assert any("leaky_handler_branch" in f.message for f in flow.findings)
    assert any("leaky_alias" in f.message for f in flow.findings)
    syntactic = run_lint(paths=[bad], checkers=["lease-lifecycle"], use_allowlist=False)
    leaky = [f for f in syntactic.findings if f.line <= 19]  # leaky_decode's block
    assert not leaky, (
        "lease-lifecycle now sees leaky_decode — fold the fixtures "
        f"together or repoint this test: {leaky}"
    )


def test_lockset_wrong_lock_annotation_is_asserted_against_inference():
    bad = FIXTURES / "lockset_inference_bad.py"
    result = run_lint(paths=[bad], checkers=["lockset-inference"], use_allowlist=False)
    msgs = [f.message for f in result.findings]
    assert any("annotation names the wrong lock" in m for m in msgs), msgs
    assert any("inconsistent inferred locksets" in m for m in msgs), msgs


def test_flow_allowlist_entries_participate_in_rot_detection():
    """ISSUE 10 satellite: the rot machinery covers the flow checkers —
    a stale lockset-inference excuse fails the run like any other."""
    stale = Allow(
        "lockset-inference", "transport/tcp.py",
        "this line does not exist anywhere",
        why="fixture: deliberately stale",
    )
    result = run_lint(allowlist=(*ALLOWLIST, stale))
    rot = [f for f in result.findings if f.checker == "allowlist-rot"]
    assert len(rot) == 1 and "lockset-inference" in rot[0].message


def test_changed_mode_is_fast_and_clean():
    """ISSUE 10 satellite budgets: an incremental run over one touched
    file (plus the cross-file companions) must land under 2 s on this
    box — the pre-commit latency the full-tree budget cannot give."""
    from psana_ray_tpu.lint.core import INCREMENTAL_COMPANIONS

    touched = REPO_ROOT / "psana_ray_tpu" / "utils" / "metrics.py"
    companions = [REPO_ROOT / rel for rel in INCREMENTAL_COMPANIONS]
    result = run_lint(paths=[touched, *companions], use_cache=True)
    assert not result.findings, result.findings
    # measures 1.1-1.5 s idle on this box; pinned with the same ~2.5x
    # loaded-box headroom the full-tree budget carries (a tier-1 run
    # sharing the core was observed to push this to ~3 s)
    assert result.duration_s < 4.0, (
        f"changed-files run took {result.duration_s:.2f}s — seconds-not-"
        f"tens-of-seconds is what makes --changed viable as a pre-commit "
        f"hook"
    )


def test_changed_cli_selects_companions_and_exits_clean():
    from psana_ray_tpu.lint.core import changed_target_files

    try:
        paths = changed_target_files("HEAD")
    except RuntimeError as e:
        pytest.skip(f"git unavailable here: {e}")
    rels = {p.resolve().relative_to(REPO_ROOT).as_posix() for p in paths}
    if rels:  # companions ride along whenever anything is selected
        assert "psana_ray_tpu/transport/tcp.py" in rels
        assert "psana_ray_tpu/transport/evloop.py" in rels
    proc = _cli("--changed", "HEAD")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # a bad ref is a usage error (exit 2), never findings (exit 1)
    assert _cli("--changed", "no-such-ref-xyzzy").returncode == 2


def test_parse_cache_hits_and_invalidates_on_edit(tmp_path):
    import ast as ast_mod

    from psana_ray_tpu.lint.cache import ParseCache

    target = tmp_path / "mod.py"
    target.write_text("def f():\n    return 1\n")
    cache = ParseCache(root=tmp_path / ".cache")
    src = target.read_text()
    assert cache.get(target, "mod.py", src) is None  # cold
    tree = ast_mod.parse(src)
    cache.put(target, "mod.py", src, tree)
    hit = cache.get(target, "mod.py", src)
    assert hit is not None and ast_mod.dump(hit) == ast_mod.dump(tree)
    # an edit invalidates by CONTENT even with a forged stat
    target.write_text("def f():\n    return 2\n")
    assert cache.get(target, "mod.py", target.read_text()) is None
    # ...and findings stay correct through the cache (end to end)
    bad = tmp_path / "bad.py"
    bad.write_text("def f():\n    return undefined_name_xyz\n")
    r1 = run_lint(paths=[bad], checkers=["undefined-name"], use_allowlist=False)
    r2 = run_lint(paths=[bad], checkers=["undefined-name"], use_allowlist=False)
    assert len(r1.findings) == len(r2.findings) == 1


def test_sarif_round_trips_findings():
    """ISSUE 10 satellite: --sarif emits SARIF 2.1.0 whose results
    reconstruct the exact findings (rule id, path, line, message, hint
    via the properties bag)."""
    from psana_ray_tpu.lint.sarif import (
        SARIF_VERSION,
        findings_from_sarif,
        to_sarif,
    )

    bad = FIXTURES / "wire_protocol_bad.py"
    result = run_lint(paths=[bad], checkers=["wire-protocol"], use_allowlist=False)
    assert result.findings
    doc = to_sarif(result)
    assert doc["version"] == SARIF_VERSION and "$schema" in doc
    run = doc["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert "wire-protocol" in rule_ids
    for res in run["results"]:
        region = res["locations"][0]["physicalLocation"]["region"]
        assert region["startLine"] >= 1
    back = findings_from_sarif(doc)
    assert [
        (f.checker, f.path, f.line, f.message, f.hint) for f in back
    ] == [
        (f.checker, f.path, f.line, f.message, f.hint) for f in result.findings
    ]
    # the clean run still emits a valid (empty-results) document
    clean = run_lint(paths=[FIXTURES / "wire_protocol_good.py"],
                     checkers=["wire-protocol"], use_allowlist=False)
    empty = to_sarif(clean)
    assert empty["runs"][0]["results"] == []


def test_sarif_cli_flag_emits_parseable_document():
    bad = FIXTURES / "wire_protocol_bad.py"
    proc = _cli("--sarif", "--no-allowlist", str(bad))
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["runs"][0]["results"], doc


def test_duration_covers_parsing_not_just_checking():
    # the <5s budget must measure what an operator waits for: a full run
    # spends most of its time reading+parsing, which duration_s includes
    full = run_lint()
    assert full.duration_s > 0
    sub = run_lint(paths=[FIXTURES / "wire_protocol_good.py"])
    assert sub.duration_s < full.duration_s
