"""Multi-host infeed: 2 real processes, jax.distributed, one global mesh.

Proves the non-degenerate branch of ``make_global_batch`` (SURVEY.md §7
hard parts (c)/(d)): two coordinator-rendezvoused processes, 4 virtual
CPU devices each, assemble per-host local shards into one global
``jax.Array`` over an 8-device mesh and reduce across it SPMD. This is
the same call path a v5e-16 pod runs (4 hosts x 4 chips), minus ICI.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_global_batch():
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(rank), "2"],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for rank in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out.decode(errors="replace"))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"MULTIHOST OK rank={rank}" in out, out
