"""Multi-host infeed: 2 real processes, jax.distributed, one global mesh.

Proves the non-degenerate branch of ``make_global_batch`` (SURVEY.md §7
hard parts (c)/(d)): two coordinator-rendezvoused processes, 4 virtual
CPU devices each, assemble per-host local shards into one global
``jax.Array`` over an 8-device mesh and reduce across it SPMD. This is
the same call path a v5e-16 pod runs (4 hosts x 4 chips), minus ICI.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(scenario: str, ok_marker: str):
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(rank), "2", scenario],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for rank in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out.decode(errors="replace"))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"{ok_marker} rank={rank}" in out, out


@pytest.mark.slow
def test_two_process_global_batch():
    _run_workers("batch", "MULTIHOST OK")


@pytest.mark.slow
def test_two_process_streaming_loop_uneven_tails():
    """The assembled loop (round-2 VERDICT missing #2): per-host producers
    -> local queues -> GlobalStreamConsumer -> SPMD step across 2 real
    jax.distributed processes, with one host's stream 4 frames shorter
    than the other's (it must pad its tail rounds and stop on the same
    round)."""
    _run_workers("stream", "MULTIHOST-STREAM OK")


@pytest.mark.slow
def test_two_process_multi_detector_fanin():
    """Multi-host × multi-detector (round-3 VERDICT weak #5): two real
    jax.distributed processes each run TWO detector streams (different
    geometries, uneven lengths per host and per detector) through
    MultiDetectorGlobalConsumer's deterministic collective schedule."""
    _run_workers("fanin", "MULTIHOST-FANIN OK")


def test_multi_detector_global_consumer_single_host():
    """Degenerate single-process check of the same composition: two
    detector legs, uneven lengths, per-detector steps, exact counts."""
    import threading
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from psana_ray_tpu.infeed.multihost import (
        GlobalStreamConsumer,
        MultiDetectorGlobalConsumer,
    )
    from psana_ray_tpu.parallel import create_mesh
    from psana_ray_tpu.records import EndOfStream, FrameRecord
    from psana_ray_tpu.transport import RingBuffer

    mesh = create_mesh(("data",), (jax.device_count(),))
    dets = {"a": ((1, 4, 8), 10), "b": ((2, 2, 8), 5)}
    queues = {name: RingBuffer(maxsize=8) for name in dets}

    def produce(name):
        shape, n = dets[name]
        for i in range(n):
            while not queues[name].put(
                FrameRecord(0, i, np.full(shape, i + 1.0, np.float32), 9.5)
            ):
                time.sleep(0.001)
        assert queues[name].put_wait(EndOfStream(total_events=n), timeout=30.0)

    threads = [threading.Thread(target=produce, args=(n,), daemon=True) for n in dets]
    for t in threads:
        t.start()

    legs = {
        name: GlobalStreamConsumer(
            queues[name], local_batch_size=8, mesh=mesh, frame_shape=dets[name][0]
        )
        for name in dets
    }
    sums = {name: 0.0 for name in dets}

    def make_step(name):
        @jax.jit
        def s(frames, valid):
            m = valid.astype(jnp.float32).reshape(-1, *([1] * (frames.ndim - 1)))
            return jnp.sum(frames * m)

        return lambda batch: s(batch.frames, batch.valid)

    counts = MultiDetectorGlobalConsumer(legs).run(
        {name: make_step(name) for name in dets},
        on_result=lambda name, out, g: sums.__setitem__(
            name, sums[name] + float(out)
        ),
    )
    for t in threads:
        t.join(timeout=30)
    assert counts == {"a": 10, "b": 5}
    for name, (shape, n) in dets.items():
        want = sum((i + 1.0) * np.prod(shape) for i in range(n))
        assert sums[name] == pytest.approx(want), name


def test_multi_detector_requires_step_coverage():
    import jax

    from psana_ray_tpu.infeed.multihost import (
        GlobalStreamConsumer,
        MultiDetectorGlobalConsumer,
    )
    from psana_ray_tpu.parallel import create_mesh
    from psana_ray_tpu.transport import RingBuffer

    mesh = create_mesh(("data",), (jax.device_count(),))
    leg = GlobalStreamConsumer(
        RingBuffer(maxsize=4), local_batch_size=2, mesh=mesh, frame_shape=(1, 4, 4)
    )
    with pytest.raises(KeyError, match="no step"):
        MultiDetectorGlobalConsumer({"a": leg}).run({})


def test_global_stream_consumer_single_host_degenerate():
    """Same consumer code on a single-process mesh: make_global_batch
    degenerates to a sharded device_put, the loop and termination
    protocol are identical."""
    import threading
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from psana_ray_tpu.infeed import GlobalStreamConsumer
    from psana_ray_tpu.parallel import create_mesh
    from psana_ray_tpu.records import EndOfStream, FrameRecord
    from psana_ray_tpu.transport import RingBuffer

    mesh = create_mesh(("data",), (8,))
    shape = (1, 4, 8)
    n = 11  # not a multiple of the local batch: padded tail round
    q = RingBuffer(maxsize=8)

    def produce():
        for i in range(n):
            frame = np.full(shape, float(i + 1), np.float32)
            while not q.put(FrameRecord(0, i, frame, 9.5)):
                time.sleep(0.001)
        assert q.put_wait(EndOfStream(total_events=n), timeout=30.0)

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    consumer = GlobalStreamConsumer(q, local_batch_size=8, mesh=mesh, frame_shape=shape)

    @jax.jit
    def _row_sums(frames, valid):
        m = valid.astype(jnp.float32)[:, None, None, None]
        return jnp.sum(frames * m, axis=(1, 2, 3))

    step = lambda batch: _row_sums(batch.frames, batch.valid)  # noqa: E731

    sums = []
    got = consumer.run(
        step, on_result=lambda out, g: sums.extend(np.asarray(out).tolist())
    )
    t.join(timeout=30)
    assert got == n
    px = float(np.prod(shape))
    assert sorted(v for v in sums if v > 0) == [px * (i + 1) for i in range(n)]


def test_global_stream_consumer_wedge_degrades_then_raises():
    """A local transport wedge must not strand peers in the collective:
    the consumer degrades to padding rounds (terminating the global loop)
    and re-raises the wedge only after the loop winds down."""
    import jax.numpy as jnp
    import numpy as np

    from psana_ray_tpu.infeed import GlobalStreamConsumer
    from psana_ray_tpu.parallel import create_mesh
    from psana_ray_tpu.transport import TransportWedged

    mesh = create_mesh(("data",), (8,))

    class WedgedQueue:
        def get_batch(self, n, timeout=None):
            raise TransportWedged("peer crashed mid-claim")

    consumer = GlobalStreamConsumer(
        WedgedQueue(), local_batch_size=8, mesh=mesh, frame_shape=(1, 4, 8)
    )
    calls = []
    with pytest.raises(TransportWedged):
        consumer.run(lambda b: calls.append(b))
    assert calls == []  # no step ran on garbage; loop terminated first


class _StallingQueue:
    """Serves ``records`` then goes silent forever — a live-but-silent
    producer leg: the transport is healthy, data just stops, no EOS."""

    def __init__(self, records):
        import threading

        self._records = list(records)
        self._lock = threading.Lock()

    def get_batch(self, n, timeout=None):
        import time

        with self._lock:
            out, self._records = self._records[:n], self._records[n:]
        if not out and timeout:
            time.sleep(timeout)
        return out

    def size(self):
        return len(self._records)


def test_global_stream_consumer_stall_timeout_degrades_then_raises():
    """Liveness guard (VERDICT r4 weak #6): a silent leg with
    ``stall_timeout_s`` set degrades to padding — terminating the global
    loop in bounded time — and the StreamStalled error surfaces AFTER the
    wind-down, with every pre-stall frame already processed."""
    import numpy as np

    from psana_ray_tpu.infeed import GlobalStreamConsumer
    from psana_ray_tpu.infeed.batcher import StreamStalled
    from psana_ray_tpu.parallel import create_mesh
    from psana_ray_tpu.records import FrameRecord

    mesh = create_mesh(("data",), (8,))
    shape = (1, 4, 8)
    recs = [
        FrameRecord(0, i, np.full(shape, i + 1.0, np.float32), 9.5)
        for i in range(8)
    ]
    consumer = GlobalStreamConsumer(
        _StallingQueue(recs), local_batch_size=8, mesh=mesh,
        frame_shape=shape, poll_interval_s=0.01, stall_timeout_s=0.3,
    )
    seen = []
    with pytest.raises(StreamStalled, match="no EOS"):
        consumer.run(lambda b: None, on_result=lambda out, g: seen.append(g))
    # the full pre-stall batch was processed before the guard fired
    assert sum(int(np.asarray(g.valid).sum()) for g in seen) == len(recs)


def test_multi_detector_stalled_leg_does_not_block_healthy_legs():
    """One wedged ingest node must not hang the pod: the stalled
    detector degrades to padding while the healthy detector streams to
    completion; the stall re-raises after the loop with full counts."""
    import threading
    import time

    import numpy as np

    from psana_ray_tpu.infeed.batcher import StreamStalled
    from psana_ray_tpu.infeed.multihost import (
        GlobalStreamConsumer,
        MultiDetectorGlobalConsumer,
    )
    from psana_ray_tpu.parallel import create_mesh
    from psana_ray_tpu.records import EndOfStream, FrameRecord
    from psana_ray_tpu.transport import RingBuffer

    mesh = create_mesh(("data",), (8,))
    shape = (1, 4, 8)
    n_healthy = 20
    healthy_q = RingBuffer(maxsize=8)
    stalled_q = _StallingQueue(
        [FrameRecord(0, i, np.zeros(shape, np.float32), 9.5) for i in range(3)]
    )

    def produce():
        for i in range(n_healthy):
            while not healthy_q.put(
                FrameRecord(0, i, np.full(shape, i + 1.0, np.float32), 9.5)
            ):
                time.sleep(0.001)
        assert healthy_q.put_wait(EndOfStream(total_events=n_healthy), timeout=30.0)

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    legs = {
        "healthy": GlobalStreamConsumer(
            healthy_q, local_batch_size=8, mesh=mesh, frame_shape=shape,
            poll_interval_s=0.01,
        ),
        "stalled": GlobalStreamConsumer(
            stalled_q, local_batch_size=8, mesh=mesh, frame_shape=shape,
            poll_interval_s=0.01, stall_timeout_s=0.3,
        ),
    }
    counts = {}

    class _Counts:
        def __call__(self, name, out, g):
            counts[name] = counts.get(name, 0) + int(np.asarray(g.valid).sum())

    with pytest.raises(StreamStalled):
        MultiDetectorGlobalConsumer(legs).run(
            {"healthy": lambda b: None, "stalled": lambda b: None},
            on_result=_Counts(),
        )
    t.join(timeout=30)
    assert counts["healthy"] == n_healthy  # streamed to completion
    assert counts.get("stalled", 0) == 3  # pre-stall frames not lost
