"""Producer runtime + DataReader client: rendezvous, backpressure,
barrier-then-EOS ordering, max_steps, masking, fault detection, metrics."""

import threading
import time

import numpy as np
import pytest

from psana_ray_tpu.config import (
    MaskConfig,
    PipelineConfig,
    RetrievalMode,
    SourceConfig,
    TransportConfig,
)
from psana_ray_tpu.consumer import DataReader, DataReaderError
from psana_ray_tpu.producer import ProducerRuntime, parse_arguments
from psana_ray_tpu.records import EndOfStream, FrameRecord, is_eos
from psana_ray_tpu.transport import Registry, RingBuffer


def _config(num_events=12, num_consumers=1, detector="epix100", **src_kw):
    return PipelineConfig(
        source=SourceConfig(
            exp="synthetic", run=1, detector_name=detector, num_events=num_events, **src_kw
        ),
        transport=TransportConfig(num_consumers=num_consumers, queue_size=64),
    )


class TestProducerRuntime:
    def test_end_to_end_all_events_then_eos(self):
        cfg = _config(num_events=10)
        rt = ProducerRuntime(cfg, num_local_shards=2)
        rt.run(block=False)

        got, eos = [], []
        with DataReader() as reader:
            while True:
                item = reader.read_wait(timeout=5.0)
                if item is None:
                    pytest.fail("starved before EOS")
                if is_eos(item):
                    eos.append(item)
                    break
                got.append(item)
        rt.join()
        # every event exactly once, EOS strictly after all data
        assert sorted(r.event_idx for r in got) == list(range(10))
        assert len(eos) == 1
        assert rt.metrics.frames.count == 10

    def test_eos_per_consumer(self):
        cfg = _config(num_events=4, num_consumers=3)
        rt = ProducerRuntime(cfg, num_local_shards=1)
        rt.run(block=True)
        q = Registry.default().resolve("default", "shared_queue", retries=1, interval_s=0.1)
        items = [q.get_wait(timeout=1.0) for _ in range(7)]
        assert sum(is_eos(i) for i in items) == 3  # parity: producer.py:124-125

    def test_max_steps(self):
        cfg = _config(num_events=100, max_steps=5)
        rt = ProducerRuntime(cfg, num_local_shards=1)
        rt.run(block=True)
        assert rt.metrics.frames.count == 5

    def test_mask_applied_host_side(self, tmp_path):
        # parity: np.where(mask, data, 0), producer.py:92-95
        mask = np.zeros((1, 704, 768), np.uint8)  # all-bad manual mask
        path = tmp_path / "mask.npy"
        np.save(path, mask)
        cfg = _config(num_events=2)
        cfg = PipelineConfig(
            source=cfg.source,
            mask=MaskConfig(manual_mask_path=str(path)),
            transport=cfg.transport,
        )
        rt = ProducerRuntime(cfg, num_local_shards=1)
        rt.run(block=True)
        with DataReader() as reader:
            rec = reader.read_wait(timeout=2.0)
        assert rec.panels.sum() == 0

    def test_queue_death_mid_stream_exits_cleanly(self):
        cfg = _config(num_events=5000, detector="epix100")
        cfg.transport.queue_size = 2  # force backpressure so death is seen
        rt = ProducerRuntime(cfg, num_local_shards=1)
        q = rt.bootstrap()
        rt.run(block=False)
        time.sleep(0.2)
        Registry.default().destroy("default", "shared_queue")  # kills queue
        rt.join()  # must return, not raise/hang — parity: producer.py:112-114

    def test_sharded_ranks_disjoint(self):
        cfg = _config(num_events=9)
        rt = ProducerRuntime(cfg, num_local_shards=3)
        rt.run(block=True)
        from psana_ray_tpu.transport import EMPTY

        q = Registry.default().resolve("default", "shared_queue", retries=1, interval_s=0.1)
        recs = [
            i
            for i in iter(lambda: q.get_wait(timeout=0.5), EMPTY)
            if not is_eos(i)
        ]
        by_rank = {}
        for r in recs:
            by_rank.setdefault(r.shard_rank, []).append(r.event_idx)
        assert set(by_rank) == {0, 1, 2}
        assert sorted(sum(by_rank.values(), [])) == list(range(9))


class TestDataReaderParity:
    def test_context_manager_and_nonblocking_read(self):
        Registry.default().get_or_create("default", "shared_queue", lambda: RingBuffer(8))
        with DataReader() as reader:
            assert reader.read() is None  # empty, parity data_reader.py:35
            assert reader.size() == 0

    def test_missing_queue_raises_reader_error(self):
        cfg = TransportConfig(rendezvous_retries=2, rendezvous_interval_s=0.01)
        with pytest.raises(DataReaderError, match="could not find"):
            DataReader(queue_name="nope", config=cfg).connect()

    def test_dead_queue_maps_to_reader_error(self):
        q = Registry.default().get_or_create("default", "shared_queue", lambda: RingBuffer(8))
        reader = DataReader().connect()
        q.close()
        with pytest.raises(DataReaderError):
            reader.read()

    def test_unconnected_read_raises(self):
        with pytest.raises(DataReaderError, match="not connected"):
            DataReader().read()

    def test_iteration_stops_at_eos(self):
        q = Registry.default().get_or_create("default", "shared_queue", lambda: RingBuffer(16))
        for i in range(3):
            q.put(FrameRecord(0, i, np.zeros((1, 2, 2), np.float32), 1.0))
        q.put(EndOfStream())
        with DataReader() as reader:
            seen = [r.event_idx for r in reader]
        assert seen == [0, 1, 2]


class TestCLI:
    def test_reference_flag_spellings(self):
        cfg, args = parse_arguments(
            [
                "--exp", "synthetic", "--run", "58", "--detector_name", "epix10k2M",
                "--calib", "--uses_bad_pixel_mask", "--queue_name", "q1",
                "--queue_size", "400", "--num_consumers", "4", "--max_steps", "100",
                "--ray_namespace", "ns", "--log_level", "DEBUG",
            ]
        )
        assert cfg.source.run == 58
        assert cfg.source.mode == RetrievalMode.CALIB
        assert cfg.mask.uses_bad_pixel_mask
        assert cfg.transport.queue_size == 400
        assert cfg.transport.num_consumers == 4
        assert cfg.transport.namespace == "ns"
        assert cfg.source.max_steps == 100

    def test_defaults_rendezvous_with_data_reader(self):
        # quirk 3 fixed: producer and DataReader share ONE default surface
        cfg, _ = parse_arguments([])
        reader = DataReader()
        assert cfg.transport.queue_name == reader.queue_name
        assert cfg.transport.namespace == reader.namespace
