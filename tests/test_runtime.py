"""Producer runtime + DataReader client: rendezvous, backpressure,
barrier-then-EOS ordering, max_steps, masking, fault detection, metrics."""

import threading
import time

import numpy as np
import pytest

from psana_ray_tpu.config import (
    MaskConfig,
    PipelineConfig,
    RetrievalMode,
    SourceConfig,
    TransportConfig,
)
from psana_ray_tpu.consumer import DataReader, DataReaderError
from psana_ray_tpu.producer import ProducerRuntime, parse_arguments
from psana_ray_tpu.records import EndOfStream, FrameRecord, is_eos
from psana_ray_tpu.transport import Registry, RingBuffer


def _config(num_events=12, num_consumers=1, detector="epix100", **src_kw):
    return PipelineConfig(
        source=SourceConfig(
            exp="synthetic", run=1, detector_name=detector, num_events=num_events, **src_kw
        ),
        transport=TransportConfig(num_consumers=num_consumers, queue_size=64),
    )


class TestProducerRuntime:
    def test_end_to_end_all_events_then_eos(self):
        cfg = _config(num_events=10)
        rt = ProducerRuntime(cfg, num_local_shards=2)
        rt.run(block=False)

        got, eos = [], []
        with DataReader() as reader:
            while True:
                item = reader.read_wait(timeout=5.0)
                if item is None:
                    pytest.fail("starved before EOS")
                if is_eos(item):
                    eos.append(item)
                    break
                got.append(item)
        rt.join()
        # every event exactly once, EOS strictly after all data
        assert sorted(r.event_idx for r in got) == list(range(10))
        assert len(eos) == 1
        assert rt.metrics.frames.count == 10

    def test_eos_per_consumer(self):
        cfg = _config(num_events=4, num_consumers=3)
        rt = ProducerRuntime(cfg, num_local_shards=1)
        rt.run(block=True)
        q = Registry.default().resolve("default", "shared_queue", retries=1, interval_s=0.1)
        items = [q.get_wait(timeout=1.0) for _ in range(7)]
        assert sum(is_eos(i) for i in items) == 3  # parity: producer.py:124-125

    def test_max_steps(self):
        cfg = _config(num_events=100, max_steps=5)
        rt = ProducerRuntime(cfg, num_local_shards=1)
        rt.run(block=True)
        assert rt.metrics.frames.count == 5

    def test_mask_applied_host_side(self, tmp_path):
        # parity: np.where(mask, data, 0), producer.py:92-95
        mask = np.zeros((1, 704, 768), np.uint8)  # all-bad manual mask
        path = tmp_path / "mask.npy"
        np.save(path, mask)
        cfg = _config(num_events=2)
        cfg = PipelineConfig(
            source=cfg.source,
            mask=MaskConfig(manual_mask_path=str(path)),
            transport=cfg.transport,
        )
        rt = ProducerRuntime(cfg, num_local_shards=1)
        rt.run(block=True)
        with DataReader() as reader:
            rec = reader.read_wait(timeout=2.0)
        assert rec.panels.sum() == 0

    def test_queue_death_mid_stream_exits_cleanly(self):
        cfg = _config(num_events=5000, detector="epix100")
        cfg.transport.queue_size = 2  # force backpressure so death is seen
        rt = ProducerRuntime(cfg, num_local_shards=1)
        q = rt.bootstrap()
        rt.run(block=False)
        time.sleep(0.2)
        Registry.default().destroy("default", "shared_queue")  # kills queue
        rt.join()  # must return, not raise/hang — parity: producer.py:112-114

    def test_sharded_ranks_disjoint(self):
        cfg = _config(num_events=9)
        rt = ProducerRuntime(cfg, num_local_shards=3)
        rt.run(block=True)
        from psana_ray_tpu.transport import EMPTY

        q = Registry.default().resolve("default", "shared_queue", retries=1, interval_s=0.1)
        recs = [
            i
            for i in iter(lambda: q.get_wait(timeout=0.5), EMPTY)
            if not is_eos(i)
        ]
        by_rank = {}
        for r in recs:
            by_rank.setdefault(r.shard_rank, []).append(r.event_idx)
        assert set(by_rank) == {0, 1, 2}
        assert sorted(sum(by_rank.values(), [])) == list(range(9))


class TestDataReaderParity:
    def test_context_manager_and_nonblocking_read(self):
        Registry.default().get_or_create("default", "shared_queue", lambda: RingBuffer(8))
        with DataReader() as reader:
            assert reader.read() is None  # empty, parity data_reader.py:35
            assert reader.size() == 0

    def test_missing_queue_raises_reader_error(self):
        cfg = TransportConfig(rendezvous_retries=2, rendezvous_interval_s=0.01)
        with pytest.raises(DataReaderError, match="could not find"):
            DataReader(queue_name="nope", config=cfg).connect()

    def test_dead_queue_maps_to_reader_error(self):
        q = Registry.default().get_or_create("default", "shared_queue", lambda: RingBuffer(8))
        reader = DataReader().connect()
        q.close()
        with pytest.raises(DataReaderError):
            reader.read()

    def test_unconnected_read_raises(self):
        with pytest.raises(DataReaderError, match="not connected"):
            DataReader().read()

    def test_iteration_stops_at_eos(self):
        q = Registry.default().get_or_create("default", "shared_queue", lambda: RingBuffer(16))
        for i in range(3):
            q.put(FrameRecord(0, i, np.zeros((1, 2, 2), np.float32), 1.0))
        q.put(EndOfStream())
        with DataReader() as reader:
            seen = [r.event_idx for r in reader]
        assert seen == [0, 1, 2]


class TestCLI:
    def test_reference_flag_spellings(self):
        cfg, args = parse_arguments(
            [
                "--exp", "synthetic", "--run", "58", "--detector_name", "epix10k2M",
                "--calib", "--uses_bad_pixel_mask", "--queue_name", "q1",
                "--queue_size", "400", "--num_consumers", "4", "--max_steps", "100",
                "--ray_namespace", "ns", "--log_level", "DEBUG",
            ]
        )
        assert cfg.source.run == 58
        assert cfg.source.mode == RetrievalMode.CALIB
        assert cfg.mask.uses_bad_pixel_mask
        assert cfg.transport.queue_size == 400
        assert cfg.transport.num_consumers == 4
        assert cfg.transport.namespace == "ns"
        assert cfg.source.max_steps == 100

    def test_defaults_rendezvous_with_data_reader(self):
        # quirk 3 fixed: producer and DataReader share ONE default surface
        cfg, _ = parse_arguments([])
        reader = DataReader()
        assert cfg.transport.queue_name == reader.queue_name
        assert cfg.transport.namespace == reader.namespace


class TestMultiRuntimeEos:
    """Two producer runtimes on ONE queue: a consumer must receive every
    event from BOTH before stopping, even when one finishes far earlier
    (VERDICT r1 weak #4; reference avoided this with a global MPI barrier,
    producer.py:119-126)."""

    def _two_runtimes(self, num_events, delay_b=0.0, num_consumers=1):
        q = Registry.default().get_or_create(
            "default", "shared_queue", lambda: RingBuffer(256)
        )
        cfgs = [_config(num_events=num_events, num_consumers=num_consumers) for _ in range(2)]
        rts = [
            ProducerRuntime(
                cfgs[i], num_local_shards=1, shard_rank_offset=i, total_shards=2
            )
            for i in range(2)
        ]
        rts[0].run(block=False)

        def _delayed():
            time.sleep(delay_b)
            rts[1].run(block=True)

        # daemonic: a runtime wedged behind a starved consumer must fail
        # the test, not hang the pytest process at exit
        tb = threading.Thread(target=_delayed, daemon=True)
        tb.start()
        return rts, tb

    def test_consumer_waits_for_slow_producer(self):
        rts, tb = self._two_runtimes(num_events=10, delay_b=0.5)
        with DataReader() as reader:
            got = [r.event_idx for r in reader]
        rts[0].join()
        tb.join()
        assert sorted(got) == list(range(10))  # nothing dropped

    def test_eos_records_carry_coverage(self):
        rts, tb = self._two_runtimes(num_events=4)
        rts[0].join()
        tb.join()
        q = Registry.default().resolve("default", "shared_queue", retries=1, interval_s=0.1)
        items = []
        while True:
            item = q.get_wait(timeout=0.5)
            from psana_ray_tpu.transport import EMPTY

            if item is EMPTY:
                break
            items.append(item)
        eos = [i for i in items if is_eos(i)]
        assert {e.producer_rank for e in eos} == {0, 1}
        assert all(e.total_shards == 2 and e.shards_done == 1 for e in eos)

    # measured 6-8/30 flaky on a 1-core host at a flat 30 s join
    # (CHANGES.md). Root cause was NOT starvation: two competing
    # consumers each re-popped their own flushed sibling EOS marker
    # within one GIL slice, never handing it over — a livelock fixed at
    # the source (EosTally.flush_duplicates callers now yield after a
    # starved flush; see consumer.iter_records). Hardened here too: the
    # join deadline scales with core scarcity and the workers are
    # daemonic, so a regression fails the test instead of wedging the
    # pytest session at exit. 0/30 failures post-fix on the 1-core box.
    def test_two_consumers_two_runtimes(self):
        import os

        rts, tb = self._two_runtimes(num_events=12, delay_b=0.3, num_consumers=2)
        results = {}

        def consume(cid):
            with DataReader() as reader:
                results[cid] = [r.event_idx for r in reader]

        threads = [
            threading.Thread(target=consume, args=(c,), daemon=True) for c in range(2)
        ]
        for t in threads:
            t.start()
        # two consumers + two producer runtimes timeshare the machine:
        # give the 30 s budget a 4-way-parallelism baseline (120 s on one
        # core, 30 s at >= 4)
        join_s = 30.0 * max(1.0, 4.0 / (os.cpu_count() or 1))
        deadline = time.monotonic() + join_s
        for t in threads:
            t.join(timeout=max(0.1, deadline - time.monotonic()))
        assert not any(t.is_alive() for t in threads), (
            f"competing consumers starved past the {join_s:.0f}s join deadline"
        )
        rts[0].join()
        tb.join()
        all_idx = sorted(results[0] + results[1])
        assert all_idx == list(range(12))  # union exact, no loss, no dupes


class TestEosNeverDropped:
    def test_duplicate_eos_held_when_queue_full(self):
        """code-review r2 finding: a full queue must not swallow a sibling
        consumer's EOS marker — it is held and returned once space frees."""
        from psana_ray_tpu.records import EndOfStream, EosTally

        q = RingBuffer(maxsize=1)
        tally = EosTally()
        tally.observe(EndOfStream(producer_rank=0, shards_done=1, total_shards=2))
        dup = EndOfStream(producer_rank=0, shards_done=1, total_shards=2)
        assert not tally.process(dup)  # duplicate, stream not complete
        q.put("blocker")  # queue full
        tally.flush_duplicates(q)  # cannot place it yet
        assert q.size() == 1
        q.get()  # space frees
        tally.flush_duplicates(q)
        assert is_eos(q.get())  # marker survived for the sibling

    def test_iter_records_stop_leaves_frames_for_siblings(self):
        q = Registry.default().get_or_create("default", "shared_queue", lambda: RingBuffer(16))
        for i in range(6):
            q.put(FrameRecord(0, i, np.zeros((1, 2, 2), np.float32), 1.0))
        q.put(EndOfStream())
        seen = []
        with DataReader() as reader:
            for rec in reader.iter_records(stop=lambda: len(seen) >= 3):
                seen.append(rec.event_idx)
        assert seen == [0, 1, 2]
        assert q.size() == 4  # 3 frames + EOS untouched for siblings


class TestShardTopology:
    """CLI shard topology: mpirun/srun rank-derived (code-review r2 —
    previously unreachable from the CLI, making the README's multi-process
    flow duplicate events and under-deliver EOS)."""

    def test_explicit_flags_win(self, monkeypatch):
        from psana_ray_tpu.producer import shard_topology

        monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "3")
        monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "4")
        _, args = parse_arguments(
            ["--num_shards", "2", "--shard_rank_offset", "10", "--total_shards", "20"]
        )
        assert shard_topology(args) == (10, 20)

    def test_mpi_env_derives_topology(self, monkeypatch):
        from psana_ray_tpu.producer import shard_topology

        monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "2")
        monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "4")
        _, args = parse_arguments(["--num_shards", "2"])
        assert shard_topology(args) == (4, 8)  # rank*local, world*local

    def test_slurm_env(self, monkeypatch):
        from psana_ray_tpu.producer import shard_topology

        for var in ("OMPI_COMM_WORLD_RANK", "PMI_RANK"):
            monkeypatch.delenv(var, raising=False)
        monkeypatch.setenv("SLURM_PROCID", "1")
        monkeypatch.setenv("SLURM_NTASKS", "3")
        _, args = parse_arguments([])
        assert shard_topology(args) == (1, 3)

    def test_no_launcher_single_process(self, monkeypatch):
        from psana_ray_tpu.producer import shard_topology

        for var in ("OMPI_COMM_WORLD_RANK", "PMI_RANK", "SLURM_PROCID"):
            monkeypatch.delenv(var, raising=False)
        _, args = parse_arguments(["--num_shards", "3"])
        assert shard_topology(args) == (0, 3)


class TestBatchedProducerPath:
    def test_producer_over_tcp_uses_batched_puts(self):
        """Over tcp:// the producer must move N frames per round trip
        (code-review r2: put_batch was dead code on the product path)."""
        from psana_ray_tpu.transport.ring import RingBuffer
        from psana_ray_tpu.transport.tcp import TcpQueueServer

        srv = TcpQueueServer(RingBuffer(256), host="127.0.0.1").serve_background()
        try:
            cfg = _config(num_events=20)
            cfg.transport.address = f"tcp://127.0.0.1:{srv.port}"
            rt = ProducerRuntime(cfg, num_local_shards=1)
            rt.run(block=True)
            # config (namespace, queue_name) now selects a NAMED queue on
            # the server (OPEN opcode) — the default queue stays untouched
            assert srv.queue.stats()["puts"] == 0
            named = srv.open_named(cfg.transport.namespace, cfg.transport.queue_name)
            # server saw far fewer put RPCs than frames (batch size 16)
            stats = named.stats()
            assert stats["puts"] == 21  # 20 frames + 1 EOS landed
            drained = [named.get() for _ in range(21)]
            idx = [r.event_idx for r in drained if not is_eos(r)]
            assert sorted(idx) == list(range(20))
            assert sum(is_eos(r) for r in drained) == 1
        finally:
            srv.shutdown()

    def test_sender_retries_partial_batch_accept(self):
        from psana_ray_tpu.producer import _Sender
        from psana_ray_tpu.transport.backoff import BackoffPolicy
        from psana_ray_tpu.transport.ring import RingBuffer
        from psana_ray_tpu.utils.metrics import PipelineMetrics

        class BatchRing(RingBuffer):  # RingBuffer + put_batch surface
            def put_batch(self, items):
                n = 0
                for it in items:
                    if not self.put(it):
                        break
                    n += 1
                return n

        q = BatchRing(maxsize=4)
        stop = threading.Event()
        sender = _Sender(q, BackoffPolicy(0.001, 0.002, 0.0), stop, PipelineMetrics(), 8)
        recs = [FrameRecord(0, i, np.zeros((1, 2, 2), np.float32), 1.0) for i in range(8)]
        drained = []

        def drain_later():
            time.sleep(0.05)
            while len(drained) < 8:
                item = q.get_wait(timeout=1.0)
                drained.append(item)

        t = threading.Thread(target=drain_later)
        t.start()
        for r in recs:
            assert sender.send(r)
        assert sender.flush()
        t.join()
        assert [r.event_idx for r in drained] == list(range(8))  # FIFO kept
