"""Sharded init/infer/train over the virtual 8-device mesh: params land in
their TP shardings, inference is batch-DP, training reduces grads across
the data axis and actually learns."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from psana_ray_tpu.models import PeakNetUNet, ResNet18
from psana_ray_tpu.models.losses import masked_sigmoid_focal, masked_softmax_xent
from psana_ray_tpu.parallel import ShardingRules, create_mesh
from psana_ray_tpu.parallel.mesh import local_batch_slice
from psana_ray_tpu.parallel.steps import (
    create_train_state,
    init_sharded,
    make_infer_step,
    make_train_step,
)


@pytest.fixture(scope="module")
def mesh():
    return create_mesh(("data", "model"), (4, 2))


class TestMeshBasics:
    def test_axis_inference(self):
        m = create_mesh(("data", "model"), (-1, 2))
        assert m.shape == {"data": 4, "model": 2}

    def test_bad_shape_rejected(self):
        with pytest.raises(ValueError):
            create_mesh(("data",), (3,))
        with pytest.raises(ValueError):
            create_mesh(("a", "b"), (-1, -1))

    def test_local_batch_slice_validates_data_axis(self, mesh):
        assert local_batch_slice(16, mesh) == 16  # single process
        with pytest.raises(ValueError, match="data axis"):
            local_batch_slice(6, mesh)  # 6 % 4 != 0


class TestShardingRules:
    def test_spec_degrades_missing_axes(self, mesh):
        rules = ShardingRules()
        # 'seq' axis not on this mesh -> replicated, not an error
        spec = rules.spec(("batch", "seq"), mesh)
        assert spec == P("data", None)

    def test_channels_out_to_model(self, mesh):
        spec = ShardingRules().spec(("height", "width", "channels_in", "channels_out"), mesh)
        assert spec == P(None, None, None, "model")


class TestShardedInitAndInfer:
    def test_params_are_tp_sharded(self, mesh):
        model = ResNet18(num_classes=2, width=32)
        sample = jnp.ones((8, 32, 32, 4))
        variables = init_sharded(model, jax.random.key(0), sample, mesh)
        # find a conv kernel and check its output-channel axis is split
        kernel = variables["params"]["stem"]["kernel"]
        spec = kernel.sharding.spec
        assert spec[-1] == "model", f"stem kernel spec {spec}"
        # each shard holds half the output channels
        shard = next(iter(kernel.addressable_shards)).data
        assert shard.shape[-1] == kernel.shape[-1] // 2

    def test_infer_matches_unsharded(self, mesh):
        # float32 so sharded-vs-host differences are pure reduction-order
        # noise (bf16 would add ~1e-2 scatter and mask real bugs)
        model = ResNet18(num_classes=3, width=16, dtype=jnp.float32)
        sample = jnp.ones((8, 32, 32, 2))
        variables = init_sharded(model, jax.random.key(1), sample, mesh)
        step = make_infer_step(model, mesh)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 32, 32, 2)), jnp.float32)
        sharded_out = np.asarray(step(variables, x))
        # same params gathered to host, plain apply
        host_vars = jax.tree.map(np.asarray, variables)
        plain_out = np.asarray(model.apply(host_vars, x))
        np.testing.assert_allclose(sharded_out, plain_out, atol=1e-4)


class TestShardedTraining:
    def test_resnet_loss_decreases(self, mesh):
        model = ResNet18(num_classes=2, width=16)
        sample = jnp.ones((8, 32, 32, 1))
        opt = optax.adam(1e-3)
        state = create_train_state(model, opt, jax.random.key(0), sample, mesh)

        rng = np.random.default_rng(0)
        # learnable rule: class = 1 if mean intensity > 0
        x = rng.normal(size=(8, 32, 32, 1)).astype(np.float32)
        x[:4] += 0.8
        labels = jnp.asarray([1, 1, 1, 1, 0, 0, 0, 0])
        valid = jnp.ones((8,), jnp.uint8)
        xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("data")))

        step = make_train_step(
            model, opt, lambda logits, aux: masked_softmax_xent(logits, aux[0], aux[1])
        )
        losses = []
        for _ in range(12):
            state, loss = step(state, xs, (labels, valid))
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, f"no learning: {losses}"
        assert int(state.step) == 12

    def test_unet_train_step_runs(self, mesh):
        model = PeakNetUNet(features=(4, 8), num_classes=1)
        sample = jnp.ones((8, 16, 32, 1))
        opt = optax.sgd(1e-2)
        state = create_train_state(model, opt, jax.random.key(0), sample, mesh)
        x = jax.device_put(sample, NamedSharding(mesh, P("data")))
        targets = jnp.zeros((8, 16, 32, 1))
        step = make_train_step(
            model, opt, lambda logits, aux: masked_sigmoid_focal(logits, aux[0], aux[1])
        )
        state, loss = step(state, x, (targets, jnp.ones((8,))))
        assert np.isfinite(float(loss))


def test_train_step_remat_matches_plain(mesh):
    """jax.checkpoint must change memory, not math: one remat step equals
    one plain step bit-for-bit given identical init."""
    import numpy as np
    import optax

    from psana_ray_tpu.models import ResNet18, panels_to_nhwc
    from psana_ray_tpu.models.losses import masked_softmax_xent
    from psana_ray_tpu.parallel.steps import create_train_state, make_train_step

    model = ResNet18(num_classes=2, width=16)
    frames = jnp.asarray(
        np.random.default_rng(0).normal(size=(8, 2, 16, 16)).astype(np.float32)
    )
    x = panels_to_nhwc(frames)
    labels = jnp.asarray(np.arange(8) % 2)
    valid = jnp.ones((8,), jnp.uint8)
    opt = optax.sgd(1e-2)
    loss_fn = lambda logits, aux: masked_softmax_xent(logits, aux[0], aux[1])  # noqa: E731

    out = {}
    for name, use_remat in (("plain", False), ("remat", True)):
        state = create_train_state(model, opt, jax.random.key(0), x, mesh)
        step = make_train_step(model, opt, loss_fn, donate=False, remat=use_remat)
        state, loss = step(state, x, (labels, valid))
        out[name] = (float(loss), state)
    assert out["plain"][0] == out["remat"][0]
    flat_p = jax.tree.leaves(out["plain"][1].variables)
    flat_r = jax.tree.leaves(out["remat"][1].variables)
    for a, b in zip(flat_p, flat_r):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_unet_tpu_train_step_runs(mesh):
    """The MXU-shaped PeakNet-TPU (models/unet_tpu.py) must be trainable
    with the same sharded train-step machinery as the classic model —
    GroupNorm form, focal segmentation loss, batch sharded P('data')."""
    import optax

    from psana_ray_tpu.models import PeakNetUNetTPU

    model = PeakNetUNetTPU(features=(4, 8), num_classes=1, norm="group")
    sample = jnp.ones((8, 16, 32, 1))
    opt = optax.sgd(1e-2)
    state = create_train_state(model, opt, jax.random.key(0), sample, mesh)
    x = jax.device_put(sample, NamedSharding(mesh, P("data")))
    targets = jnp.zeros((8, 16, 32, 1))
    step = make_train_step(
        model, opt, lambda logits, aux: masked_sigmoid_focal(logits, aux[0], aux[1])
    )
    state, loss = step(state, x, (targets, jnp.ones((8,))))
    assert np.isfinite(float(loss))
    assert int(state.step) == 1
