"""Durable queue server integration tests (ISSUE 8): committed offsets
over the wire, kill -9 crash-restart with zero loss and exact resume,
replay for a second consumer group, bounded spill through the relay,
fault-proxy-driven recovery, and coordinator-state persistence."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from faultproxy import FaultProxy
from psana_ray_tpu.obs.flight import FLIGHT
from psana_ray_tpu.records import EndOfStream, FrameRecord, is_eos
from psana_ray_tpu.storage import DurableRingBuffer, SegmentLog
from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer


def _rec(i, shape=(1, 16, 16)):
    return FrameRecord(0, i, np.full(shape, i, np.uint16), 9.5)


def _durable_server(root, maxsize=500, ram_items=None, **log_kw):
    log_kw.setdefault("segment_bytes", 1 << 20)
    log_kw.setdefault("fsync", "none")

    def factory(ns, name, maxsize_):
        log = SegmentLog(
            os.path.join(str(root), f"{ns}__{name}"), name=name, **log_kw
        )
        return DurableRingBuffer(
            log, maxsize=maxsize_, name=name, ram_items=ram_items
        )

    srv = TcpQueueServer(
        factory("default", "default", maxsize),
        host="127.0.0.1", maxsize=maxsize, queue_factory=factory,
        group_store_path=os.path.join(str(root), "groups.json"),
    ).serve_background()
    return srv


def _drain(client, timeout=1.0):
    out = []
    while True:
        batch = client.get_batch(64, timeout=timeout)
        if not batch:
            return out
        out.extend(batch)
        if any(is_eos(x) for x in batch):
            return out


class TestCommittedOffsets:
    def test_implicit_ack_commits_over_the_wire(self, tmp_path):
        srv = _durable_server(tmp_path)
        try:
            prod = TcpQueueClient("127.0.0.1", srv.port)
            for i in range(10):
                assert prod.put(_rec(i))
            cons = TcpQueueClient("127.0.0.1", srv.port)
            got = cons.get_batch(4, timeout=1.0)
            assert len(got) == 4
            # nothing committed yet: the response is still in flight
            assert srv.queue.stats()["committed_offset"] == -1
            cons.size()  # the next opcode IS the ack
            assert srv.queue.stats()["committed_offset"] == 3
            prod.disconnect()
            cons.disconnect()
        finally:
            srv.shutdown()

    def test_consumer_death_without_ack_redelivers(self, tmp_path):
        srv = _durable_server(tmp_path)
        try:
            prod = TcpQueueClient("127.0.0.1", srv.port)
            for i in range(8):
                assert prod.put(_rec(i))
            cons = TcpQueueClient("127.0.0.1", srv.port)
            got = cons.get_batch(3, timeout=1.0)
            assert len(got) == 3
            cons._sock.close()  # crash: no BYE, no next opcode, no ack
            cons2 = TcpQueueClient("127.0.0.1", srv.port)
            deadline = time.monotonic() + 5.0
            redelivered = []
            while len(redelivered) < 8 and time.monotonic() < deadline:
                redelivered.extend(cons2.get_batch(8, timeout=0.25))
            # requeue-at-head within this life; floor never moved
            assert [r.event_idx for r in redelivered] == list(range(8))
            cons2.size()
            assert srv.queue.stats()["committed_offset"] == 7
            prod.disconnect()
            cons2.disconnect()
        finally:
            srv.shutdown()

    def test_stream_cumulative_ack_commits(self, tmp_path):
        srv = _durable_server(tmp_path)
        try:
            prod = TcpQueueClient("127.0.0.1", srv.port)
            for i in range(6):
                assert prod.put(_rec(i))
            cons = TcpQueueClient("127.0.0.1", srv.port)
            reader = cons.stream_open(window=8)
            first = reader.get_batch_stream(6, timeout=2.0)
            # acked when the consumer comes back for more
            reader.get_batch_stream(1, timeout=0.1)
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline:
                if srv.queue.stats()["committed_offset"] == len(first) - 1:
                    break
                time.sleep(0.02)
            assert srv.queue.stats()["committed_offset"] == len(first) - 1
            prod.disconnect()
            cons.disconnect()
        finally:
            srv.shutdown()


class TestCrashRestart:
    """kill -9 the queue-server PROCESS mid-stream, restart on the same
    --durable_dir, assert zero loss and exact resume at the committed
    offset — the ISSUE 8 acceptance row."""

    @staticmethod
    def _start(durable_dir, port_file, fsync="batch"):
        if os.path.exists(port_file):
            os.remove(port_file)
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "psana_ray_tpu.queue_server",
                "--port", "0", "--durable_dir", durable_dir,
                "--fsync", fsync, "--fsync_batch_n", "8",
                "--port_file", port_file, "--stall_poll_s", "0",
                "--queue_size", "500",
                "--segment_bytes", str(1 << 20),
            ],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            stderr=subprocess.DEVNULL,
        )
        deadline = time.monotonic() + 30
        while not os.path.exists(port_file):
            assert proc.poll() is None, "queue server died on startup"
            assert time.monotonic() < deadline, "server never wrote port file"
            time.sleep(0.05)
        return proc, int(open(port_file).read())

    def test_kill9_zero_loss_exact_resume(self, tmp_path):
        durable_dir = str(tmp_path / "log")
        port_file = str(tmp_path / "port")
        proc, port = self._start(durable_dir, port_file)
        try:
            prod = TcpQueueClient(
                "127.0.0.1", port, namespace="ns", queue_name="q",
                reconnect_tries=1,
            )
            # windowed pipelined puts with sampled fsync points (batch=8)
            for i in range(60):
                assert prod.put_pipelined(_rec(i))
            assert prod.flush_puts()
            cons = TcpQueueClient(
                "127.0.0.1", port, namespace="ns", queue_name="q",
                reconnect_tries=1,
            )
            first = cons.get_batch(25, timeout=2.0)
            cons.size()  # implicit-ack: committed offset = 24
            assert len(first) == 25

            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)

            t0 = time.monotonic()
            proc, port = self._start(durable_dir, port_file)
            recovery_s = time.monotonic() - t0
            cons2 = TcpQueueClient(
                "127.0.0.1", port, namespace="ns", queue_name="q",
                reconnect_tries=1,
            )
            rest = _drain(cons2)
            idxs = sorted(r.event_idx for r in rest)
            # exact resume at the committed offset: 25..59, no loss, and
            # no redelivery of the acked prefix either
            assert idxs == list(range(25, 60)), (
                f"lost={sorted(set(range(25, 60)) - set(idxs))} "
                f"dup={len(idxs) - len(set(idxs))}"
            )
            assert recovery_s < 30
            cons2.disconnect()
        finally:
            if proc.poll() is None:
                os.kill(proc.pid, signal.SIGKILL)
                proc.wait(timeout=10)

    def test_torn_tail_repair_breadcrumb_on_reboot(self, tmp_path):
        # build a log, corrupt the last record on disk, reboot the
        # backing: the scan must truncate and leave the breadcrumb
        log = SegmentLog(
            str(tmp_path / "q"), segment_bytes=1 << 20, fsync="none", name="q"
        )
        q = DurableRingBuffer(log, maxsize=64, name="q")
        for i in range(5):
            q.put(_rec(i))
        seg = log._segments[-1]
        pos = seg.find(4)
        path = seg.path
        log.close()
        with open(path, "r+b") as f:
            f.seek(pos + 30)
            f.write(b"\xff\xff\xff\xff")
        n0 = FLIGHT.event_count
        log2 = SegmentLog(
            str(tmp_path / "q"), segment_bytes=1 << 20, fsync="none", name="q"
        )
        q2 = DurableRingBuffer(log2, maxsize=64, name="q")
        kinds = [e["kind"] for e in FLIGHT.events()]
        assert "torn_tail_repair" in kinds and "recovery_scan" in kinds
        assert FLIGHT.event_count > n0
        # the 4 intact records re-expose; the torn 5th redelivers via the
        # producer-side resend contract, never silently served
        assert [r.event_idx for r in q2.get_batch(16, timeout=0)] == [0, 1, 2, 3]
        log2.close()


class TestReplay:
    def test_second_group_replays_from_begin_without_disturbing_live(
        self, tmp_path
    ):
        srv = _durable_server(tmp_path)
        try:
            prod = TcpQueueClient("127.0.0.1", srv.port)
            for i in range(12):
                assert prod.put(_rec(i))
            prod.put(EndOfStream(total_events=12))
            live = TcpQueueClient("127.0.0.1", srv.port)
            first_live = live.get_batch(5, timeout=1.0)
            live.size()  # ack

            rep = TcpQueueClient("127.0.0.1", srv.port)
            info = rep.replay_open("begin", group="model-v2")
            assert info["start"] == 0
            replayed = _drain(rep)
            idxs = [getattr(r, "event_idx", "EOS") for r in replayed]
            assert idxs == [*range(12), "EOS"]  # the FULL retained range
            assert rep.commit_offset() is True

            # live consumption continues exactly where it was
            rest_live = _drain(live)
            live_idxs = [getattr(r, "event_idx", "EOS") for r in rest_live]
            assert live_idxs == [*range(5, 12), "EOS"]
            for c in (prod, live, rep):
                c.disconnect()
        finally:
            srv.shutdown()

    def test_replay_resume_continues_after_crash(self, tmp_path):
        srv = _durable_server(tmp_path)
        try:
            prod = TcpQueueClient("127.0.0.1", srv.port)
            for i in range(10):
                assert prod.put(_rec(i))
            rep = TcpQueueClient("127.0.0.1", srv.port)
            rep.replay_open("begin", group="g2")
            first = rep.get_batch(4, timeout=1.0)
            rep.size()  # implicit ack commits g2 through offset 3
            # crash the replay consumer without BYE
            rep._sock.close()
            rep2 = TcpQueueClient("127.0.0.1", srv.port)
            rep2.replay_open("resume", group="g2")
            rest = rep2.get_batch(32, timeout=1.0)
            assert [r.event_idx for r in first] == [0, 1, 2, 3]
            assert [r.event_idx for r in rest] == [4, 5, 6, 7, 8, 9]
            prod.disconnect()
            rep2.disconnect()
        finally:
            srv.shutdown()

    def test_replay_open_on_streamed_connection_refused(self, tmp_path):
        srv = _durable_server(tmp_path)
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            c.stream_open(window=4)
            with pytest.raises(RuntimeError, match="streamed"):
                c.replay_open("begin")
            c.disconnect()
        finally:
            srv.shutdown()

    def test_oversized_record_errors_without_killing_the_loop(self, tmp_path):
        # a record bigger than segment_bytes raises ValueError inside the
        # durable queue; via the PARKED put path ('U' against a full
        # queue) that exception must answer THIS client with a protocol
        # error — not escape the pump and take down the whole server
        srv = _durable_server(tmp_path, maxsize=1, segment_bytes=1 << 16)
        try:
            filler = TcpQueueClient("127.0.0.1", srv.port)
            assert filler.put(_rec(0))  # queue (maxsize=1) now full
            big = _rec(1, shape=(8, 64, 64))  # 64 KB payload > 64 KB segment
            blocked = TcpQueueClient("127.0.0.1", srv.port)
            with pytest.raises(RuntimeError, match="protocol error"):
                # parks as a 'U' waiter, then the pump's put raises when
                # space frees
                import threading as _t

                def free_soon():
                    time.sleep(0.3)
                    drainer = TcpQueueClient("127.0.0.1", srv.port)
                    drainer.get_batch(4, timeout=1.0)
                    drainer.disconnect()

                _t.Thread(target=free_soon, daemon=True).start()
                blocked.put_wait(big, timeout=5.0)
            # the loop survived: a fresh client still gets served
            probe = TcpQueueClient("127.0.0.1", srv.port)
            assert isinstance(probe.size(), int)
            for c in (filler, probe):
                c.disconnect()
        finally:
            srv.shutdown()

    def test_replay_refused_on_memory_only_queue(self, tmp_path):
        from psana_ray_tpu.transport.ring import RingBuffer

        srv = TcpQueueServer(RingBuffer(10), host="127.0.0.1").serve_background()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            with pytest.raises(RuntimeError, match="no segment log"):
                c.replay_open("begin")
            c.disconnect()
        finally:
            srv.shutdown()


class TestSpillThroughRelay:
    def test_depth_beyond_ram_arrives_intact(self, tmp_path):
        srv = _durable_server(tmp_path, maxsize=300, ram_items=8)
        try:
            prod = TcpQueueClient("127.0.0.1", srv.port)
            for i in range(120):
                assert prod.put_pipelined(_rec(i))
            assert prod.flush_puts()
            st = srv.queue.stats()
            assert st["spilled"] >= 100 and st["resident"] <= 8
            cons = TcpQueueClient("127.0.0.1", srv.port)
            got = []
            while len(got) < 120:
                batch = cons.get_batch(64, timeout=1.0)
                if not batch:
                    break
                got.extend(batch)
            assert [r.event_idx for r in got] == list(range(120))
            # spilled frames decode byte-exact
            assert np.array_equal(got[100].panels, _rec(100).panels)
            prod.disconnect()
            cons.disconnect()
        finally:
            srv.shutdown()


class TestFaultProxyDriven:
    def test_kill_at_byte_mid_put_loses_nothing(self, tmp_path):
        """Sever the producer wire mid-record: the windowed-put resend
        plus the durable floor must deliver every frame, holes never."""
        srv = _durable_server(tmp_path)
        proxy = FaultProxy("127.0.0.1", srv.port)
        try:
            prod = TcpQueueClient("127.0.0.1", proxy.port)
            wire_one = len(b"".join(
                bytes(p) for p in __import__(
                    "psana_ray_tpu.transport.codec", fromlist=["*"]
                ).encode_payload_parts(_rec(0))
            ))
            # cut mid-way through the 5th frame's payload
            fault = proxy.kill_at("up", int(4.5 * wire_one))
            for i in range(20):
                assert prod.put_pipelined(_rec(i))
            assert prod.flush_puts()
            assert fault.fired
            cons = TcpQueueClient("127.0.0.1", srv.port)
            got = []
            while True:
                batch = cons.get_batch(64, timeout=0.5)
                if not batch:
                    break
                got.extend(batch)
            idxs = [r.event_idx for r in got]
            assert sorted(set(idxs)) == list(range(20)), "holes!"
            prod.disconnect()
            cons.disconnect()
        finally:
            proxy.close()
            srv.shutdown()

    def test_stall_injection_rides_backpressure(self, tmp_path):
        srv = _durable_server(tmp_path)
        proxy = FaultProxy("127.0.0.1", srv.port)
        try:
            prod = TcpQueueClient("127.0.0.1", proxy.port)
            proxy.stall_at("up", 1024, stall_s=0.4)
            t0 = time.monotonic()
            for i in range(8):
                assert prod.put(_rec(i))
            assert time.monotonic() - t0 >= 0.3  # the stall really bit
            cons = TcpQueueClient("127.0.0.1", srv.port)
            got = cons.get_batch(16, timeout=1.0)
            assert [r.event_idx for r in got] == list(range(8))
            prod.disconnect()
            cons.disconnect()
        finally:
            proxy.close()
            srv.shutdown()


class TestCoordinatorPersistence:
    def test_registry_recovers_groups_from_store(self, tmp_path):
        from psana_ray_tpu.cluster.coordinator import GroupRegistry

        store = str(tmp_path / "groups.json")
        reg = GroupRegistry(store_path=store)
        resp = reg.handle(
            {"op": "join", "group": "g", "member": "m1", "n_partitions": 4}
        )
        gen = resp["generation"]
        reg.handle({
            "op": "drained", "group": "g", "member": "m1",
            "generation": gen, "partition": 2, "offset": 41,
        })
        # coordinator restart: a FRESH registry over the same store
        reg2 = GroupRegistry(store_path=store)
        info = reg2.handle({"op": "info", "group": "g"})
        assert info["n_partitions"] == 4
        assert info["drained"] == [2]
        assert info["offsets"] == {"2": 41}
        # generations continue monotonically: stale members stay fenced
        assert info["generation"] >= gen
        fenced = reg2.handle({
            "op": "drained", "group": "g", "member": "m1",
            "generation": gen - 1, "partition": 3,
        })
        assert fenced.get("fenced") is True

    def test_midstream_recovery_survives_the_first_rejoin(self, tmp_path):
        """The recovered drained/offsets state must NOT be wiped by the
        new-epoch heuristic when members rejoin after a coordinator
        restart — their EOS markers are already consumed; nobody could
        ever re-commit the drained partitions."""
        from psana_ray_tpu.cluster.coordinator import GroupRegistry

        store = str(tmp_path / "groups.json")
        reg = GroupRegistry(store_path=store)
        gen = reg.handle(
            {"op": "join", "group": "g", "member": "m1", "n_partitions": 4}
        )["generation"]
        reg.handle({
            "op": "drained", "group": "g", "member": "m1",
            "generation": gen, "partition": 1, "offset": 7,
        })
        # coordinator restart MID-STREAM (drain incomplete: 1 of 4)
        reg2 = GroupRegistry(store_path=store)
        resp = reg2.handle(
            {"op": "join", "group": "g", "member": "m1", "n_partitions": 4}
        )
        assert resp["drained"] == [1], "recovered drain progress was wiped"
        assert resp["offsets"] == {"1": 7}
        # but a FINISHED run reusing the group name after a restart is
        # a new epoch: the stale complete drain set must clear
        reg3 = GroupRegistry(store_path=store)
        gen3 = reg3.handle(
            {"op": "join", "group": "g2", "member": "m", "n_partitions": 2}
        )["generation"]
        for part in (0, 1):
            gen3 = reg3.handle({
                "op": "drained", "group": "g2", "member": "m",
                "generation": gen3, "partition": part,
            })["generation"]
        reg3.handle({"op": "leave", "group": "g2", "member": "m"})
        reg4 = GroupRegistry(store_path=store)
        fresh = reg4.handle(
            {"op": "join", "group": "g2", "member": "m9", "n_partitions": 2}
        )
        assert fresh["drained"] == [], "finished-run state leaked into a new epoch"

    def test_memory_only_registry_still_forgets(self, tmp_path):
        from psana_ray_tpu.cluster.coordinator import GroupRegistry

        reg = GroupRegistry()
        reg.handle({"op": "join", "group": "g", "member": "m", "n_partitions": 2})
        reg2 = GroupRegistry()
        assert reg2.handle({"op": "info", "group": "g"}).get("unknown_group")


class TestClusterMigration:
    def test_add_server_drains_log_backed_partitions(self, tmp_path):
        from psana_ray_tpu.cluster.client import ClusterClient

        servers = [
            _durable_server(tmp_path / f"s{i}", maxsize=200) for i in range(5)
        ]
        try:
            addrs = [f"127.0.0.1:{s.port}" for s in servers[:2]]
            prod = ClusterClient(addrs, queue_name="q", n_partitions=8, maxsize=200)
            for i in range(30):
                assert prod.put_pipelined(_rec(i))
            assert prod.put(EndOfStream(total_events=30))
            cons = ClusterClient(addrs, queue_name="q", n_partitions=8, maxsize=200)
            # rendezvous hashing may hand a particular newcomer nothing
            # (placement is a function of the random ephemeral ports):
            # keep growing until one actually wins a partition
            moved = 0
            for s in servers[2:]:
                moved = cons.add_server(f"127.0.0.1:{s.port}")
                if moved:
                    break
            assert moved > 0  # a newcomer won something
            seen = []
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                batch = cons.get_batch(32, timeout=1.0)
                if not batch:
                    continue
                done = False
                for r in batch:
                    if is_eos(r):
                        done = True
                    else:
                        seen.append(r.event_idx)
                if done:
                    break
            # the PR 7 gap is closed for log-backed queues: nothing the
            # old owner still held is stranded
            assert sorted(set(seen)) == list(range(30))
            prod.disconnect()
            cons.disconnect()
        finally:
            for s in servers:
                s.shutdown()
