"""SLO-aware serving gateway (ISSUE 12): admission, deadline shedding,
weighted fair-share, the escalate/restore cycle, and the zero-copy pin
through the gateway path.

The gateway tests run against an INJECTED clock and a simulated device
(dispatch advances the clock by the operating point's service time), so
control behavior — what gets admitted, shed, and served at which batch
size — is deterministic on a loaded box. The transport-level WDRR test
runs against a real event-loop server with raw streamed sockets so the
tenant hello is exercised both ways on the wire.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from faultproxy import OpenLoopLoad, arrival_schedule
from psana_ray_tpu.obs.flight import FLIGHT
from psana_ray_tpu.obs.stall import StallDetector
from psana_ray_tpu.records import EndOfStream, FrameRecord
from psana_ray_tpu.serving import (
    GatewayTelemetry,
    PATH_ADMISSION,
    PATH_DEADLINE,
    PATH_STALL,
    ServingGateway,
    SloPolicy,
    make_batch_dispatch,
)
from psana_ray_tpu.transport.ring import RingBuffer
from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer
from psana_ray_tpu.utils.bufpool import WIRE, BufferPool

OPS = ((1, 0.89), (2, 1.43), (4, 2.45), (8, 4.33))


def _rec(idx=0, shape=(2, 4, 8), dtype=np.float32, rank=0, energy=9.5):
    panels = np.arange(int(np.prod(shape)), dtype=dtype).reshape(shape) + idx
    return FrameRecord(rank, idx, panels, energy, timestamp=1.25)


class _SimClock:
    """Injectable monotonic clock + a simulated device: dispatch
    advances time by the operating point's service latency."""

    def __init__(self, policy=None):
        self.t = 0.0
        self.policy = policy
        self.dispatched = []  # (tenant-agnostic) record lists
        self.batch_sizes = []

    def __call__(self):
        return self.t

    def device(self, recs, batch_size):
        self.dispatched.extend(recs)
        self.batch_sizes.append(batch_size)
        self.t += dict(OPS)[batch_size] / 1000.0


def _gateway(slo_ms=25.0, weights=None, **kw):
    policy = SloPolicy(slo_ms=slo_ms, operating_points=OPS, ewma=0.0)
    clock = _SimClock(policy)
    gw = ServingGateway(
        clock.device, policy=policy, weights=weights, clock=clock,
        telemetry=GatewayTelemetry(register=False), **kw
    )
    return gw, clock


# ---------------------------------------------------------------------------
# policy: the frontier as a control law
# ---------------------------------------------------------------------------

class TestSloPolicy:
    def test_idle_serves_b1_loaded_serves_b8(self):
        p = SloPolicy(operating_points=OPS)
        assert p.choose_batch(0) == 1
        assert p.choose_batch(1) == 1
        assert p.choose_batch(3) == 2
        assert p.choose_batch(8) == 8
        assert p.choose_batch(10_000) == 8

    def test_slo_guard_steps_down_an_unservable_point(self):
        # B8's device time alone exceeds a 3 ms SLO: never choose it
        p = SloPolicy(slo_ms=3.0, operating_points=OPS)
        assert p.choose_batch(10_000) == 4

    def test_observe_service_refines_the_table(self):
        p = SloPolicy(operating_points=OPS, ewma=1.0)
        p.observe_service(8, 10.0)
        assert p.service_ms(8) == pytest.approx(10.0)
        assert p.capacity_fps() == pytest.approx(
            max(8 / 10.0 * 1000.0, 4 / 2.45 * 1000.0)
        )

    def test_budget_shrinks_while_degraded(self):
        p = SloPolicy(slo_ms=20.0, shed_margin=0.9, degraded_margin=0.5)
        assert p.budget_ms(False) == pytest.approx(18.0)
        assert p.budget_ms(True) == pytest.approx(10.0)

    def test_validation(self):
        with pytest.raises(ValueError):  # duplicate batch size
            SloPolicy(operating_points=[(2, 0.5), (2, 1.0)])
        with pytest.raises(ValueError):  # non-positive service time
            SloPolicy(operating_points=[(1, 0.0)])
        with pytest.raises(ValueError):
            SloPolicy(slo_ms=0)
        with pytest.raises(ValueError):  # margins out of order
            SloPolicy(shed_margin=0.4, degraded_margin=0.6)


# ---------------------------------------------------------------------------
# gateway: admission, deadlines, adaptivity
# ---------------------------------------------------------------------------

class TestGatewayControl:
    def test_idle_frame_dispatches_at_b1(self):
        gw, clock = _gateway()
        assert gw.offer(_rec(0))
        assert gw.dispatch_once() == 1
        assert clock.batch_sizes == [1]
        assert gw.telemetry.stats()["batch_last"] == 1

    def test_backlog_dispatches_at_b8(self):
        gw, clock = _gateway(slo_ms=1000.0)
        for i in range(16):
            assert gw.offer(_rec(i))
        gw.dispatch_once()
        assert clock.batch_sizes == [8]

    def test_admission_sheds_past_the_budget_and_conserves(self):
        gw, clock = _gateway(slo_ms=25.0)
        admitted = shed = 0
        for i in range(500):  # one instant: far beyond an SLO of backlog
            if gw.offer(_rec(i)):
                admitted += 1
            else:
                shed += 1
        assert 0 < admitted < 100  # ~a budget's worth, not everything
        assert shed == 500 - admitted
        while gw.dispatch_once():
            pass
        s = gw.telemetry.stats()
        assert s["offered_total"] == 500
        assert s["offered_total"] == s["completed_total"] + s["shed_total"]
        assert s["shed_admission_total"] == shed
        # everything admitted completed INSIDE the SLO (that is what the
        # admission predicate promised)
        assert s["goodput_total"] == s["completed_total"]
        assert s["slo_attainment"] == 1.0

    def test_dequeue_recheck_sheds_aged_out_frames_loudly(self):
        gw, clock = _gateway(slo_ms=25.0)
        for i in range(4):
            assert gw.offer(_rec(i))
        before = FLIGHT.count_of("gateway_shed")
        clock.t += 1.0  # everything aged out while queued
        handled = gw.dispatch_once()
        assert handled == 4
        assert clock.dispatched == []  # never processed late
        s = gw.telemetry.stats()
        assert s["shed_deadline_total"] == 4
        assert FLIGHT.count_of("gateway_shed") > before

    def test_explicit_deadline_beats_the_slo_default(self):
        gw, clock = _gateway(slo_ms=1000.0)
        assert not gw.offer(_rec(0), deadline=clock.t + 0.0001)
        s = gw.telemetry.stats()
        assert s["shed_admission_total"] == 1

    def test_service_feedback_reaches_the_policy(self):
        gw, clock = _gateway(slo_ms=1000.0)
        gw.policy._ewma = 1.0  # full-step for the pin
        assert gw.offer(_rec(0))
        gw.dispatch_once()
        # the simulated device took exactly the B1 point; EWMA kept it
        assert gw.policy.service_ms(1) == pytest.approx(0.89, rel=0.05)


class TestShedNeverSilent:
    """ISSUE 12 satellite: every shed path increments the SAME counter
    family and leaves a breadcrumb; the conservation identity holds
    across all three."""

    def test_all_three_paths_count_and_crumb_and_conserve(self):
        gw, clock = _gateway(slo_ms=25.0)
        crumbs0 = FLIGHT.count_of("gateway_shed")
        offered = 0
        # path 1 — admission: flood one instant far past the budget
        for i in range(300):
            gw.offer(_rec(i))
            offered += 1
        # path 2 — stall escalation: a frame that fits the NORMAL budget
        # but not the degraded one. Drain most of the backlog first so
        # predicted sojourn sits between the two budgets.
        while gw.backlog() > 20:
            gw.dispatch_once()
        gw.escalate("test")
        assert gw.degraded
        stall_shed = 0
        for i in range(40):
            if not gw.offer(_rec(1000 + i)):
                stall_shed += 1
            offered += 1
        gw.restore()
        assert not gw.degraded
        # path 3 — dequeue age-out: park admitted frames past deadline
        clock.t += 1.0
        while gw.dispatch_once():
            pass
        s = gw.telemetry.stats()
        by_path = gw.telemetry.shed_by_path()
        assert by_path[PATH_ADMISSION] > 0
        assert by_path[PATH_STALL] > 0 and by_path[PATH_STALL] == stall_shed
        assert by_path[PATH_DEADLINE] > 0
        assert s["shed_total"] == sum(by_path.values())
        # conservation: nothing silent anywhere
        assert s["offered_total"] == offered
        assert gw.backlog() == 0
        assert s["offered_total"] == s["completed_total"] + s["shed_total"]
        # each path left at least one breadcrumb (first shed always does)
        assert FLIGHT.count_of("gateway_shed") >= crumbs0 + 3


class TestWeightedFairShare:
    def test_goodput_tracks_weights_under_sustained_overload(self):
        """3:1 weights, equal offered load at ~2.2x capacity: goodput
        shares converge to the weights within 10%."""
        gw, clock = _gateway(slo_ms=20.0, weights={"a": 3, "b": 1})
        rate = 2000.0  # per tenant; capacity ~1848 total => ~2.2x
        next_at = {"a": 0.0, "b": 0.0}
        i = 0
        while clock.t < 2.0:
            for t in ("a", "b"):
                while next_at[t] <= clock.t:
                    gw.offer(_rec(i), tenant=t)
                    next_at[t] += 1.0 / rate
                    i += 1
            if gw.dispatch_once() == 0:
                clock.t += 0.001
        goodput = gw.telemetry.tenant_goodput()
        share = goodput["a"] / max(1, goodput["a"] + goodput["b"])
        assert 0.75 * 0.9 <= share <= min(1.0, 0.75 * 1.1), goodput
        s = gw.telemetry.stats()
        # overload: real shedding happened, and loudly
        assert s["shed_total"] > 0
        assert s["offered_total"] == (
            s["completed_total"] + s["shed_total"] + gw.backlog()
        )


# ---------------------------------------------------------------------------
# stall detector: escalate / restore acts on the gateway
# ---------------------------------------------------------------------------

class _FakeQueue:
    def __init__(self):
        self.depth = 0
        self.maxsize = 8
        self.puts = 0
        self.gets = 0

    def stats(self):
        return {
            "depth": self.depth, "maxsize": self.maxsize,
            "puts": self.puts, "gets": self.gets,
        }


class TestStallEscalation:
    def test_fire_escalates_clear_restores(self):
        gw, _clock = _gateway()
        cleared = []
        det = StallDetector(
            full_threshold_s=1.0, idle_threshold_s=1.0,
            on_clear=lambda: cleared.append(True),
        )
        q = _FakeQueue()
        det.watch("q", q).bind_gateway(gw)
        # healthy polls: nothing happens
        q.depth, q.puts, q.gets = 2, 10, 8
        det.poll_once(now=100.0)
        assert not det.degraded and not gw.degraded
        # queue pegs at maxsize past the threshold: fire
        q.depth, q.puts = q.maxsize, 20
        det.poll_once(now=101.0)
        det.poll_once(now=103.0)
        assert det.degraded
        assert det.snapshot()["degraded"] == 1
        assert gw.degraded  # the detector ACTED, not just warned
        assert gw.telemetry.stats()["escalations"] == 1
        # condition clears: restore
        q.depth, q.gets = 1, 25
        det.poll_once(now=104.0)
        assert not det.degraded
        assert det.snapshot()["degraded"] == 0
        assert not gw.degraded
        assert cleared == [True]
        assert gw.telemetry.stats()["restores"] == 1

    def test_bind_mid_episode_escalates_immediately(self):
        det = StallDetector(full_threshold_s=0.5)
        q = _FakeQueue()
        q.depth = q.maxsize
        det.watch("q", q)
        det.poll_once(now=10.0)
        det.poll_once(now=11.0)
        assert det.degraded
        gw, _ = _gateway()
        det.bind_gateway(gw)
        assert gw.degraded

    def test_dead_queue_cannot_latch_the_degraded_gauge(self):
        """A queue whose transport dies (stats raises) or that leaves
        the watch population mid-episode must not hold bound gateways
        escalated forever — its unobservable episode is dropped."""
        gw, _ = _gateway()
        det = StallDetector(full_threshold_s=0.5)
        q = _FakeQueue()
        q.depth = q.maxsize
        det.watch("q", q).bind_gateway(gw)
        det.poll_once(now=10.0)
        det.poll_once(now=11.0)
        assert det.degraded and gw.degraded
        # the transport dies: stats() raises from now on
        def _boom():
            raise RuntimeError("transport closed")
        q.stats = _boom
        det.poll_once(now=12.0)
        assert not det.degraded
        assert not gw.degraded
        # same for a queue that simply vanishes from a provider
        det2 = StallDetector(full_threshold_s=0.5)
        pop = {"q": _FakeQueue()}
        pop["q"].depth = pop["q"].maxsize
        det2.watch_provider(lambda: pop)
        det2.poll_once(now=20.0)
        det2.poll_once(now=21.0)
        assert det2.degraded
        pop.clear()
        det2.poll_once(now=22.0)
        assert not det2.degraded


class TestDispatchSerialization:
    def test_run_thread_and_drain_never_reenter_the_dispatch(self):
        """dispatch callables (make_batch_dispatch's FrameBatcher
        arenas) are not thread-safe: a run() loop racing a drain()
        caller must serialize through the gateway, never re-enter."""
        concurrent = []
        active = threading.Semaphore(1)

        def dispatch(recs, batch_size):
            if not active.acquire(blocking=False):
                concurrent.append(True)
                return
            try:
                time.sleep(0.002)
            finally:
                active.release()

        gw = ServingGateway(
            dispatch,
            policy=SloPolicy(slo_ms=10_000.0, operating_points=OPS),
            telemetry=GatewayTelemetry(register=False),
        )
        stop = threading.Event()
        loop = threading.Thread(target=gw.run, args=(stop,), daemon=True)
        loop.start()
        for i in range(200):
            gw.offer(_rec(i))
            if i % 16 == 0:
                gw.drain(deadline_s=0.01)  # racing dispatcher
        gw.drain(deadline_s=10.0)
        stop.set()
        loop.join(timeout=5)
        assert not concurrent
        assert gw.backlog() == 0
        s = gw.telemetry.stats()
        assert s["completed_total"] == 200 and s["shed_total"] == 0


class TestTenantArgs:
    def test_weight_without_tenant_refuses_loudly(self):
        import argparse

        from psana_ray_tpu.config import TransportConfig
        from psana_ray_tpu.transport.addressing import (
            add_tenant_args,
            apply_tenant_args,
        )

        p = argparse.ArgumentParser()
        add_tenant_args(p)
        cfg = TransportConfig()
        # weight with no tenant: refuse, never silently drop
        with pytest.raises(ValueError, match="requires --tenant"):
            apply_tenant_args(cfg, p.parse_args(["--tenant_weight", "8"]))
        # out-of-range weight validated even without a tenant
        with pytest.raises(ValueError, match="1, 64"):
            apply_tenant_args(cfg, p.parse_args(["--tenant_weight", "999"]))
        # the good path round-trips
        out = apply_tenant_args(
            cfg, p.parse_args(["--tenant", "a", "--tenant_weight", "8"])
        )
        assert out.tenant == "a" and out.tenant_weight == 8
        # defaults pass through untouched
        assert apply_tenant_args(cfg, p.parse_args([])) is cfg


# ---------------------------------------------------------------------------
# batch adapter: fixed-shape batches, padded tails, zero-copy
# ---------------------------------------------------------------------------

class TestMakeBatchDispatch:
    def test_pads_partial_dispatches_and_reuses_per_size_batchers(self):
        batches = []
        dispatch = make_batch_dispatch(batches.append)
        dispatch([_rec(0), _rec(1), _rec(2)], 4)
        assert len(batches) == 1
        assert batches[0].frames.shape[0] == 4
        assert batches[0].num_valid == 3
        assert list(batches[0].valid) == [1, 1, 1, 0]
        dispatch([_rec(3)], 1)
        assert batches[1].num_valid == 1 and batches[1].batch_size == 1
        # a full dispatch emits exactly once, unpadded
        dispatch([_rec(i) for i in range(4, 8)], 4)
        assert batches[2].num_valid == 4


class TestGatewayTransportPath:
    """serve_queue: the consumer drive path behind a gateway — EOS
    semantics and the zero-copy pins, over a real TCP server."""

    def _run_gateway_relay(self, n, pool=None, slo_ms=10_000.0):
        q = RingBuffer(64)
        srv = TcpQueueServer(q, host="127.0.0.1", pool=pool).serve_background()
        prod = TcpQueueClient("127.0.0.1", srv.port, pool=pool)
        cons = TcpQueueClient("127.0.0.1", srv.port, pool=pool)
        batches = []
        gw = ServingGateway(
            make_batch_dispatch(batches.append),
            policy=SloPolicy(slo_ms=slo_ms, operating_points=OPS),
            telemetry=GatewayTelemetry(register=False),
        )
        try:
            def produce():
                for i in range(n):
                    assert prod.put_wait(_rec(i, shape=(2, 16, 16)), timeout=30)
                assert prod.put_wait(EndOfStream(total_events=n), timeout=30)

            t = threading.Thread(target=produce, daemon=True)
            c0 = WIRE.stats()
            t.start()
            gw.serve_queue(cons, max_wait_s=60.0)
            t.join(timeout=30)
            d = WIRE.stats()
            return gw, batches, (
                d["copies_total"] - c0["copies_total"],
                d["bytes_copied_total"] - c0["bytes_copied_total"],
            )
        finally:
            prod.disconnect()
            cons.disconnect()
            srv.shutdown()
            from psana_ray_tpu.transport.ring import EMPTY as _EMPTY

            while True:  # redelivered at-least-once tail: release leases
                item = q.get()
                if item is _EMPTY:
                    break
                release = getattr(item, "release", None)
                if release is not None:
                    release()

    def test_serve_queue_processes_everything_and_stops_at_eos(self):
        n = 24
        gw, batches, _ = self._run_gateway_relay(n)
        seen = sum(b.num_valid for b in batches)
        assert seen == n
        s = gw.telemetry.stats()
        assert s["offered_total"] == n
        assert s["completed_total"] == n and s["shed_total"] == 0

    def test_zero_copy_pins_hold_through_the_gateway(self):
        """Acceptance: copies/frame == 1.00 (the one batch-arena
        memcpy) and steady-state pool churn == 0 through serve_queue +
        make_batch_dispatch — the gateway adds control, not copies."""
        pool = BufferPool()
        n = 24
        gw, batches, (copies, nbytes) = self._run_gateway_relay(n, pool=pool)
        assert sum(b.num_valid for b in batches) == n
        assert copies == n, f"expected exactly 1 copy/frame, got {copies}/{n}"
        assert nbytes == n * _rec(0, shape=(2, 16, 16)).nbytes
        s = pool.stats()
        assert s["churn_misses"] == 0, (
            f"gateway path churned {s['churn_misses']} allocations ({s})"
        )


# ---------------------------------------------------------------------------
# transport WDRR: the tenant hello on the wire, weighted stream pump
# ---------------------------------------------------------------------------

def _subscribe_raw(port, advert, window):
    s = socket.create_connection(("127.0.0.1", port), timeout=30.0)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    payload = advert.encode()
    s.sendall(b"Z" + struct.pack("<H", len(payload)) + payload)
    assert _recv_exact(s, 1) == b"1"
    (k,) = struct.unpack("<H", _recv_exact(s, 2))
    chosen = _recv_exact(s, k).decode()
    assert chosen == "none"
    s.sendall(b"M" + struct.pack("<I", window))
    return s


def _recv_exact(s, n):
    buf = b""
    while len(buf) < n:
        chunk = s.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _count_pushes(s, counts, idx):
    """Read pushed frames off a raw streamed socket until it goes quiet."""
    s.settimeout(1.0)
    try:
        while True:
            st = _recv_exact(s, 1)
            assert st == b"1"
            _seq, ln = struct.unpack("<QI", _recv_exact(s, 12))
            _recv_exact(s, ln)
            counts[idx] += 1
    except (socket.timeout, ConnectionError):
        return


class TestEvloopWdrr:
    def test_tenant_hello_reaches_the_server(self):
        q = RingBuffer(16)
        srv = TcpQueueServer(q, host="127.0.0.1").serve_background()
        try:
            before = FLIGHT.count_of("tenant_hello")
            c = TcpQueueClient(
                "127.0.0.1", srv.port, tenant="alice", tenant_weight=4
            )
            assert c.put(_rec(0))
            assert FLIGHT.count_of("tenant_hello") == before + 1
            evt = [e for e in FLIGHT.events() if e["kind"] == "tenant_hello"][-1]
            assert evt["tenant"] == "alice" and evt["weight"] == 4
            c.disconnect()
        finally:
            srv.shutdown()

    def test_tenant_name_validation(self):
        with pytest.raises(ValueError):
            TcpQueueClient("127.0.0.1", 1, tenant="a,b")
        with pytest.raises(ValueError):
            TcpQueueClient("127.0.0.1", 1, tenant="a", tenant_weight=0)
        with pytest.raises(ValueError):
            TcpQueueClient("127.0.0.1", 1, tenant="a", tenant_weight=65)

    def test_stream_pump_splits_backlog_by_tenant_weight(self):
        """Two streamed subscribers, tenants 3:1, one shared backlog:
        delivered counts converge to the weight shares — the greedy
        tenant cannot take the queue. Exercises the hello both ways on
        the wire (client advertises, server pump honors)."""
        n = 320
        q = RingBuffer(n + 8)
        srv = TcpQueueServer(q, host="127.0.0.1").serve_background()
        socks = []
        try:
            socks.append(_subscribe_raw(srv.port, "none,tenant=heavy:3", n))
            socks.append(_subscribe_raw(srv.port, "none,tenant=light:1", n))
            time.sleep(0.1)  # both subscriptions parked in the pump
            rec = _rec(0, shape=(2, 8, 8))
            for _ in range(n):
                assert q.put(rec)
            counts = [0, 0]
            threads = [
                threading.Thread(
                    target=_count_pushes, args=(s, counts, i), daemon=True
                )
                for i, s in enumerate(socks)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert sum(counts) == n, counts
            heavy_share = counts[0] / n
            assert 0.75 * 0.85 <= heavy_share <= 0.75 * 1.15, counts
        finally:
            for s in socks:
                try:
                    s.close()
                except OSError:
                    pass
            srv.shutdown()

    def test_untenanted_streams_share_the_default_budget(self):
        """No hello anywhere: pre-ISSUE-12 behavior — two anonymous
        subscribers split the backlog roughly evenly (round-robin)."""
        n = 160
        q = RingBuffer(n + 8)
        srv = TcpQueueServer(q, host="127.0.0.1").serve_background()
        socks = []
        try:
            for _ in range(2):
                s = socket.create_connection(("127.0.0.1", srv.port), timeout=30.0)
                s.sendall(b"M" + struct.pack("<I", n))
                socks.append(s)
            time.sleep(0.1)
            rec = _rec(0, shape=(2, 8, 8))
            for _ in range(n):
                assert q.put(rec)
            counts = [0, 0]
            threads = [
                threading.Thread(
                    target=_count_pushes, args=(s, counts, i), daemon=True
                )
                for i, s in enumerate(socks)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert sum(counts) == n, counts
            assert 0.3 <= counts[0] / n <= 0.7, counts
        finally:
            for s in socks:
                try:
                    s.close()
                except OSError:
                    pass
            srv.shutdown()


# ---------------------------------------------------------------------------
# open-loop burst generation (tests/faultproxy.py satellite)
# ---------------------------------------------------------------------------

class TestRateAwareAdmission:
    """ISSUE 13 satellite (the PR 12 parked follow-up): admission
    predicts from measured per-tenant arrival RATES + backlog, not
    backlog alone. A bursty tenant whose queue happens to be drained at
    the instant a competitor's frame arrives still takes its WDRR turns
    during that frame's wait — the backlog-only share over-admitted the
    competitor, and those tail admissions landed late."""

    def _burst_and_drain(self, gw, clock, tenant, n=8, at=None):
        if at is not None:
            clock.t = at
        for i in range(n):
            assert gw.offer(_rec(i), tenant=tenant)
        while gw.dispatch_once():
            pass

    def test_hot_but_drained_tenant_halves_the_predicted_share(self):
        gw, clock = _gateway(slo_ms=25.0)
        # B bursts and fully drains: queue empty, offered-rate hot
        self._burst_and_drain(gw, clock, "B", at=0.0)
        # A's admissions stop at the HALVED share: predicted sojourn
        # ceil((k+1)/8) * 4.33ms / 0.5 crosses the 22.5 ms budget at
        # k=16; backlog-only (window 0) admits well past it
        a_admitted = sum(gw.offer(_rec(i), tenant="A") for i in range(24))
        assert a_admitted == 16
        gw0, clock0 = _gateway(slo_ms=25.0, rate_window_s=0.0)
        self._burst_and_drain(gw0, clock0, "B", at=0.0)
        a0_admitted = sum(gw0.offer(_rec(i), tenant="A") for i in range(24))
        assert a0_admitted == 24  # the PR 12 behavior this satellite fixes

    def test_rate_window_expires(self):
        gw, clock = _gateway(slo_ms=25.0)
        self._burst_and_drain(gw, clock, "B", at=0.0)
        # 3 s later (window 2 s): B's burst no longer predicts
        clock.t = 3.0
        a_admitted = sum(gw.offer(_rec(i), tenant="A") for i in range(24))
        assert a_admitted == 24

    def test_offered_rate_series_exported(self):
        gw, clock = _gateway(slo_ms=1000.0)
        clock.t = 1.0
        for i in range(10):
            gw.offer(_rec(i), tenant="A")  # admitted or shed both count
        rates = gw.offered_fps_by_tenant()
        assert rates["A"] == pytest.approx(10 / 2.0)  # 10 offers / 2 s window
        stats = gw.telemetry.stats()
        assert stats["A"]["offered_fps"] == rates["A"]

    def test_ramp_schedule_rate_aware_keeps_admitted_work_in_slo(self):
        """The pin: drive tenant B with a RAMP arrival schedule
        (faultproxy.arrival_schedule, time-compressed onto the sim
        clock) against a steady tenant A. Rate-aware admission keeps
        every ADMITTED frame inside the SLO across the ramp; the
        backlog-only predictor admits A frames during B's drained
        instants whose deadlines then die to B's next burst."""

        def drive(rate_window_s):
            gw, clock = _gateway(
                slo_ms=25.0, weights={"A": 1, "B": 3},
                rate_window_s=rate_window_s,
            )
            # B ramps 600 Hz -> 4 kHz over 40 ms: the early ramp DRAINS
            # between arrivals (B1 service 0.89 ms < the ~5 ms gaps),
            # the late ramp outruns B8 capacity (~1.85 kfps) and piles
            # up. A bursts 24 frames at t=5 ms — an instant where B's
            # queue is empty but its offered-rate window is hot.
            sched = arrival_schedule("ramp", 600.0, 0.04, ramp_to_hz=4000.0)
            events = sorted(
                [(t, "B") for t in sched] + [(0.005, "A")] * 24
            )
            a_i, b_i = 0, 0
            for t, tenant in events:
                if t > clock.t:
                    # idle gap: let the device catch up before the next
                    # arrival (open-loop: arrivals never wait)
                    while clock.t < t and gw.dispatch_once():
                        pass
                    clock.t = max(clock.t, t)
                idx = a_i if tenant == "A" else b_i
                gw.offer(_rec(idx), tenant=tenant)
                if tenant == "A":
                    a_i += 1
                else:
                    b_i += 1
            while gw.dispatch_once():
                pass
            return gw.telemetry.stats()

        rate_aware = drive(rate_window_s=2.0)
        backlog_only = drive(rate_window_s=0.0)
        # conservation holds for both (shed is loud, never lost)
        for s in (rate_aware, backlog_only):
            assert s["offered_total"] == s["completed_total"] + s["shed_total"]
        # the satellite's promise: with rate in the predictor, admitted
        # work completes inside the SLO across the whole ramp...
        assert rate_aware["goodput_total"] == rate_aware["completed_total"]
        # ...and over-admission surfaces WHERE the shed happens: the
        # rate-aware door rejects doomed frames at admission (zero spent
        # on them), while the backlog-only predictor admits frames whose
        # deadlines then die to demand it could not see — they are
        # dropped at DEQUEUE after wasting queue residency (the dequeue
        # re-check is what keeps them from completing late)
        assert rate_aware["shed_deadline_total"] == 0
        assert backlog_only["shed_deadline_total"] > 0


class TestArrivalSchedules:
    def test_steady_spacing_and_count(self):
        s = arrival_schedule("steady", 100.0, 2.0)
        assert len(s) == 200
        assert s[0] == 0.0
        diffs = [b - a for a, b in zip(s, s[1:])]
        assert all(abs(d - 0.01) < 1e-9 for d in diffs)

    def test_burst_concentrates_arrivals_in_the_on_window(self):
        s = arrival_schedule(
            "burst", 100.0, 2.0, burst_factor=4.0, period_s=1.0
        )
        assert len(s) == 200
        for t in s:
            # every arrival inside the first quarter of its period
            assert (t % 1.0) <= 0.25 + 1e-9, t

    def test_ramp_is_monotonic_and_ends_hot(self):
        s = arrival_schedule("ramp", 100.0, 2.0, ramp_to_hz=200.0)
        assert len(s) == 200
        assert all(b >= a for a, b in zip(s, s[1:]))
        assert s[-1] <= 2.0
        # more arrivals in the second half than the first (the ramp)
        late = sum(1 for t in s if t >= 1.0)
        assert late > len(s) * 0.55

    def test_mean_rate_is_preserved_across_profiles(self):
        for profile in ("steady", "burst", "ramp"):
            s = arrival_schedule(profile, 50.0, 4.0)
            assert len(s) == 200, profile

    def test_burst_fractional_per_period_keeps_the_mean_rate(self):
        # rate_hz * period_s < 2: int() truncation here used to realize
        # one arrival per period (half the documented mean rate) and
        # stretch the schedule to ~2x the duration
        s = arrival_schedule("burst", 12.0, 5.0, period_s=0.15)
        assert len(s) == 60
        assert s[-1] < 5.0 + 0.15, s[-1]
        assert all(b >= a for a, b in zip(s, s[1:]))

    def test_unknown_profile_raises(self):
        with pytest.raises(ValueError):
            arrival_schedule("poisson", 10, 1)

    def test_open_loop_load_fires_the_whole_schedule(self):
        seen = {"a": 0, "b": 0}
        lock = threading.Lock()

        def submit(tenant):
            with lock:
                seen[tenant] += 1

        load = OpenLoopLoad(submit, {
            "a": arrival_schedule("steady", 400.0, 0.2),
            "b": arrival_schedule("burst", 200.0, 0.2, period_s=0.05),
        })
        offered = load.run(timeout_s=30.0)
        assert offered == {"a": 80, "b": 40}
        assert seen == offered
