"""Standalone relay load driver for the bench ``data-plane`` section.

One PROCESS per invocation — the worker-scaling A/B measures the
SERVER's multi-core data plane, so the client load must not serialize
on a single bench-process GIL. Each named queue gets a producer thread
(windowed pipelined puts) and a consumer thread (batched gets) against
``127.0.0.1:<port>``; the script prints ``<frames_relayed> <wall_s>``
on stdout and exits nonzero if any queue came up short.

Usage: relay_driver.py <port> <n_per_queue> <q1,q2,...> <HxWxD>
"""

import os
import sys
import threading
import time

import numpy as np

# invoked by script path, so sys.path[0] is tools/ — the package lives
# one level up (the repo is run in place, not installed)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from psana_ray_tpu.records import FrameRecord  # noqa: E402
from psana_ray_tpu.transport.tcp import TcpQueueClient  # noqa: E402


def pump(port, qname, n, panels, results):
    prod = TcpQueueClient(
        "127.0.0.1", port, namespace="bench", queue_name=qname,
    )
    cons = TcpQueueClient(
        "127.0.0.1", port, namespace="bench", queue_name=qname,
    )

    def produce():
        for i in range(n):
            if not prod.put_pipelined(
                FrameRecord(0, i, panels, 9.5),
                deadline=time.monotonic() + 300,
            ):
                raise RuntimeError(f"{qname}: producer starved out")
        if not prod.flush_puts(deadline=time.monotonic() + 300):
            raise RuntimeError(f"{qname}: put window never drained")

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    seen = 0
    deadline = time.monotonic() + 300
    while seen < n and time.monotonic() < deadline:
        batch = cons.get_batch(32, timeout=10.0)
        if not batch:
            continue
        seen += len(batch)
    t.join(timeout=30)
    results[qname] = seen
    prod.disconnect()
    cons.disconnect()


def main():
    port = int(sys.argv[1])
    n = int(sys.argv[2])
    queues = sys.argv[3].split(",")
    shape = tuple(int(x) for x in sys.argv[4].split("x"))
    rng = np.random.default_rng(7)
    panels = rng.integers(0, 4096, size=shape, dtype=np.uint16)

    results = {}
    threads = [
        threading.Thread(
            target=pump, args=(port, q, n, panels, results), daemon=True
        )
        for q in queues
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    total = sum(results.values())
    print(f"{total} {dt:.6f}")
    return 0 if total == n * len(queues) else 1


if __name__ == "__main__":
    sys.exit(main())
